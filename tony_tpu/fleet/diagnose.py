"""Fleet-level automatic diagnosis: one evidence-backed verdict over the
whole pool.

The per-job engine (``tony_tpu/diagnosis/``) answers "why did my job
die"; this is its fleet twin answering "why is the POOL unhealthy" —
fed by the goodput ledger (``fleet/ledger.py``) and the scheduler
decision records (``REC_FLEET_DECISION``), in the same rule-engine
style: every rule emits a Finding with the numbers that fired it, the
verdict is picked by category precedence, and an unexplained verdict is
treated as worse than none.

Verdicts (precedence order)::

    SICK_SLICE       correlated host failures cordoned a whole slice —
                     a hardware incident, evacuation in progress
    FLAKY_HOST       the failure-attribution ledger quarantined a host;
                     placements already route around it
    STARVATION       a non-quota-held job has waited far beyond the
                     median grant wait — priority/quota tuning needed
    QUOTA_SATURATED  a tenant sits at its quota with work queued behind
                     it — raise the quota or drain the tenant
    FRAGMENTATION    free hosts EXIST but do not pack into the waiting
                     gang (sub-slice locality) — min_hosts / defrag
    PREEMPT_STORM    preemptions dominate grants or one victim is
                     shrunk over and over — priority bands too close
    POOL_COLD        a warm pool is configured but starts keep going
                     cold — the pool is under-sized or mis-mounted
    SLO_BREACH       no structural pathology matched, but the alert
                     engine (``tony_tpu/alerts/``) has fleet-scope
                     rules firing — the SLO numbers are the verdict
    FLEET_HEALTHY    none of the above; goodput evidence attached

A firing alert is also *evidence*: when a structural verdict wins, any
alerts that were firing ride along in its incident as corroboration
(the ``alerts`` bundle key, fed live from the engine or offline from
replayed ``REC_FLEET_ALERT`` records).

The daemon recomputes this from its in-memory state every export and
atomically replaces ``fleet.incident.json`` (fault-gated: a rule-engine
failure degrades to no-verdict, never a blocked tick); ``tony-tpu fleet
diagnose`` rebuilds the same bundle OFFLINE from the fleet dir, so the
verdict survives the daemon. The verdict→knob table lives in
docs/operations.md ("Fleet triage").
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
from typing import Any, Callable, Dict, List, Optional

from tony_tpu import constants

log = logging.getLogger(__name__)

SICK_SLICE = "SICK_SLICE"
FLAKY_HOST = "FLAKY_HOST"
STARVATION = "STARVATION"
QUOTA_SATURATED = "QUOTA_SATURATED"
FRAGMENTATION = "FRAGMENTATION"
PREEMPT_STORM = "PREEMPT_STORM"
POOL_COLD = "POOL_COLD"
SLO_BREACH = "SLO_BREACH"
FLEET_HEALTHY = "FLEET_HEALTHY"

#: every category the engine can return (golden-matrix test anchor) in
#: precedence order, most urgent first. Hardware verdicts outrank
#: scheduling ones: a starving queue behind a cordoned slice is a
#: hardware incident, not a priority-tuning problem.
CATEGORY_PRECEDENCE = (SICK_SLICE, FLAKY_HOST, STARVATION,
                       QUOTA_SATURATED, FRAGMENTATION,
                       PREEMPT_STORM, POOL_COLD, SLO_BREACH,
                       FLEET_HEALTHY)

#: schema version stamped into fleet.incident.json.
INCIDENT_SCHEMA = 1

# --- thresholds (module constants, tunable in one place) -------------------
STARVATION_MIN_WAIT_S = 30.0     # absolute floor before anyone starves
STARVATION_FACTOR = 5.0          # × median grant wait
PREEMPT_STORM_MIN = 3            # absolute preemption floor
PREEMPT_STORM_RATIO = 0.5        # preemptions / grants
PREEMPT_STORM_PER_JOB = 3        # one victim shrunk this often
POOL_COLD_MIN_STARTS = 4         # starts before cold-fraction is signal
POOL_COLD_WARM_FRACTION = 0.5    # below this with a pool = cold

#: verdict → the knob to spend it on (rendered by the CLI/portal; the
#: full table with context is the Fleet triage runbook).
_ADVICE = {
    SICK_SLICE: "correlated failures cordoned a whole slice — file the "
                "hardware ticket, let the evacuation migrations drain "
                "it, and uncordon after repair (docs/operations.md "
                "'Host health')",
    FLAKY_HOST: "the failure-attribution ledger quarantined the host — "
                "jobs already route around it; replace or repair the "
                "hardware, then let probation's canary re-admit it "
                "(or `fleet uncordon` after a manual fix)",
    STARVATION: "a job is starving behind the queue — raise its "
                "priority, lower the blocker's, or widen the "
                "blocking tenant's quota headroom",
    QUOTA_SATURATED: "the tenant is quota-bound, not capacity-bound — "
                     "raise tony.fleet.quotas for the tenant or drain "
                     "its running jobs",
    FRAGMENTATION: "free hosts exist but do not pack — submit with "
                   "min_hosts so the scheduler can shrink-to-fit, or "
                   "prefer slice-sized gangs (the defragmentation move "
                   "is ROADMAP item 3's live migration)",
    PREEMPT_STORM: "preemption is churning the pool — widen the "
                   "priority bands or raise victims' min_hosts floors "
                   "so each shrink reclaims more",
    POOL_COLD: "starts keep going cold despite a warm pool — raise "
               "tony.pool.size (and check tony.fleet.pool-dir reaches "
               "every grant)",
    SLO_BREACH: "a fleet SLO alert is firing with no structural "
                "pathology matched — read the rule's series and the "
                "burn-rate windows (docs/operations.md 'Alerting & "
                "SLOs') before turning any scheduler knob",
    FLEET_HEALTHY: "the pool keeps up — no scheduler knob indicated",
}


@dataclasses.dataclass
class Finding:
    category: str
    rule: str
    summary: str
    confidence: float = 0.5
    evidence: List[str] = dataclasses.field(default_factory=list)
    details: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["advice"] = _ADVICE[self.category]
        return d


_RULES: List[Callable[[Dict[str, Any]], Optional[Finding]]] = []


def _rule(fn: Callable[[Dict[str, Any]], Optional[Finding]]):
    _RULES.append(fn)
    return fn


def _queued(bundle: Dict[str, Any]) -> List[Dict[str, Any]]:
    return [r for r in bundle.get("queue", []) if isinstance(r, dict)]


@_rule
def _sick_slice(b: Dict[str, Any]) -> Optional[Finding]:
    health = b.get("health") or {}
    sick = list(health.get("sick_slices") or [])
    if not sick:
        return None
    members = [r for r in health.get("cordoned", [])
               if isinstance(r, dict) and r.get("slice") in sick]
    ev = [f"health: slice(s) {sick} cordoned by correlated-failure "
          f"detection (tony.health.slice-blast-n hosts suspect inside "
          f"the blast window)"]
    for r in members[:4]:
        ev.append(f"  {r.get('host')}: {r.get('state')} "
                  f"score={r.get('score')} ({r.get('reason', '?')})")
    return Finding(SICK_SLICE, "sick-slice",
                   f"slice(s) {sick} are sick — correlated host "
                   f"failures triggered a blast-radius cordon",
                   confidence=0.95, evidence=ev,
                   details={"slices": sick,
                            "hosts": [r.get("host") for r in members]})


@_rule
def _flaky_host(b: Dict[str, Any]) -> Optional[Finding]:
    health = b.get("health") or {}
    auto = [r for r in health.get("cordoned", [])
            if isinstance(r, dict) and not r.get("manual")]
    if not auto:
        return None
    worst = auto[0]
    ev = [f"health: {len(auto)} host(s) cordoned by the "
          f"failure-attribution ledger: "
          f"{[r.get('host') for r in auto]}"]
    for e in (worst.get("evidence") or [])[-4:]:
        ev.append(f"  {worst.get('host')}: {e.get('kind', '?')} "
                  f"in {e.get('job') or '?'}")
    return Finding(FLAKY_HOST, "flaky-host",
                   f"host {worst.get('host')} is quarantined with "
                   f"attributed failures (score {worst.get('score')})",
                   confidence=0.9, evidence=ev,
                   details={"hosts": [r.get("host") for r in auto],
                            "worst": worst.get("host")})


@_rule
def _starvation(b: Dict[str, Any]) -> Optional[Finding]:
    median = float(b.get("median_grant_wait_s", 0.0) or 0.0)
    floor = max(STARVATION_MIN_WAIT_S, STARVATION_FACTOR * median)
    worst = None
    for row in _queued(b):
        if (row.get("last_decision") or {}).get("action") == "quota":
            continue             # quota-held is its own verdict
        wait = float(row.get("wait_s", 0.0) or 0.0)
        if wait >= floor and (worst is None
                              or wait > worst["wait_s"]):
            worst = {"job": row.get("job"), "wait_s": wait,
                     "decision": row.get("last_decision") or {}}
    if worst is None:
        return None
    dec = worst["decision"]
    ev = [f"queue: {worst['job']} has waited {worst['wait_s']:.0f}s "
          f"(threshold max({STARVATION_MIN_WAIT_S:.0f}s, "
          f"{STARVATION_FACTOR:.0f}x median grant wait "
          f"{median:.1f}s))"]
    if dec:
        ev.append(f"last hold: [{dec.get('action')}] "
                  f"{dec.get('reason', '?')}")
        if dec.get("blocking"):
            ev.append(f"blocking: {dec['blocking']}")
    return Finding(STARVATION, "starvation",
                   f"job {worst['job']} is starving in the queue",
                   confidence=0.85, evidence=ev,
                   details={"job": worst["job"],
                            "wait_s": round(worst["wait_s"], 1)})


@_rule
def _quota_saturated(b: Dict[str, Any]) -> Optional[Finding]:
    quotas = b.get("quotas") or {}
    used = b.get("tenants_used") or {}
    hits = []
    for row in _queued(b):
        dec = row.get("last_decision") or {}
        if dec.get("action") != "quota":
            continue
        tenant = str(row.get("tenant", "") or "")
        quota = int(quotas.get(tenant, 0) or 0)
        if quota > 0:
            hits.append((tenant, quota, row, dec))
    if not hits:
        return None
    tenant, quota, row, dec = hits[0]
    queued_jobs = sorted({str(r.get("job")) for t, _, r, _ in
                          [(h[0], h[1], h[2], h[3]) for h in hits]
                          if t == tenant})
    ev = [f"tenant {tenant!r} uses {used.get(tenant, 0)}/{quota} "
          f"quota hosts with {len(queued_jobs)} job(s) quota-held: "
          f"{queued_jobs}",
          f"last hold ({row.get('job')}): {dec.get('reason', '?')}"]
    if dec.get("blocking"):
        ev.append(f"blocking (the tenant's own running jobs): "
                  f"{dec['blocking']}")
    return Finding(QUOTA_SATURATED, "quota-saturated",
                   f"tenant {tenant!r} is saturated at its "
                   f"{quota}-host quota with work queued behind it",
                   confidence=0.9, evidence=ev,
                   details={"tenant": tenant, "quota": quota,
                            "queued": queued_jobs})


@_rule
def _fragmentation(b: Dict[str, Any]) -> Optional[Finding]:
    for row in _queued(b):
        dec = row.get("last_decision") or {}
        if dec.get("action") != "capacity":
            continue
        free = int(dec.get("free", 0) or 0)
        hosts = int(row.get("hosts", 0) or 0)
        if hosts and free >= hosts:
            ev = [f"queue: {row.get('job')} wants {hosts} host(s); "
                  f"{free} are FREE but do not pack (sub-slice gangs "
                  f"need one slice)",
                  f"hold: {dec.get('reason', '?')}"]
            if dec.get("blocking"):
                ev.append(f"largest holders: {dec['blocking']}")
            return Finding(
                FRAGMENTATION, "fragmentation",
                f"the pool has {free} free host(s) that cannot pack "
                f"a waiting {hosts}-host gang",
                confidence=0.85, evidence=ev,
                details={"job": row.get("job"), "free": free,
                         "hosts": hosts})
    return None


@_rule
def _preempt_storm(b: Dict[str, Any]) -> Optional[Finding]:
    preempts = int(b.get("preemptions_total", 0) or 0)
    grants = int(b.get("grants_total", 0) or 0)
    per_job = b.get("preempts_per_job") or {}
    worst = max(per_job.items(), key=lambda kv: kv[1]) \
        if per_job else ("", 0)
    ratio = preempts / grants if grants else 0.0
    storm = (preempts >= PREEMPT_STORM_MIN
             and ratio >= PREEMPT_STORM_RATIO) \
        or worst[1] >= PREEMPT_STORM_PER_JOB
    if not storm:
        return None
    ev = [f"counters: {preempts} preemption(s) against {grants} "
          f"grant(s) (ratio {ratio:.2f}, threshold "
          f"{PREEMPT_STORM_RATIO})"]
    if worst[1]:
        ev.append(f"worst victim: {worst[0]} shrunk {worst[1]} time(s) "
                  f"(threshold {PREEMPT_STORM_PER_JOB})")
    return Finding(PREEMPT_STORM, "preempt-storm",
                   "preempt-to-reclaim is churning the pool",
                   confidence=0.8, evidence=ev,
                   details={"preemptions": preempts, "grants": grants,
                            "worst_victim": worst[0]})


@_rule
def _pool_cold(b: Dict[str, Any]) -> Optional[Finding]:
    if not b.get("pool_dir"):
        return None
    fleet = (b.get("ledger") or {}).get("fleet") or {}
    starts = int(fleet.get("warm_starts", 0) or 0) \
        + int(fleet.get("cold_starts", 0) or 0)
    frac = fleet.get("warm_start_fraction")
    if starts < POOL_COLD_MIN_STARTS or frac is None \
            or float(frac) >= POOL_COLD_WARM_FRACTION:
        return None
    return Finding(
        POOL_COLD, "pool-cold",
        f"only {float(frac):.0%} of {starts} start(s) adopted a warm "
        f"executor despite a configured pool",
        confidence=0.75,
        evidence=[f"ledger: warm_start_fraction = {float(frac):.2f} "
                  f"over {starts} start(s) (threshold "
                  f"{POOL_COLD_WARM_FRACTION})",
                  f"pool: {b.get('pool_dir')}"],
        details={"warm_start_fraction": frac, "starts": starts})


def _firing_alerts(b: Dict[str, Any]) -> List[Dict[str, Any]]:
    return [r for r in (b.get("alerts") or [])
            if isinstance(r, dict) and r.get("state") == "firing"]


@_rule
def _slo_breach(b: Dict[str, Any]) -> Optional[Finding]:
    firing = _firing_alerts(b)
    if not firing:
        return None
    # Page-severity rules outrank warns when picking the headline.
    firing = sorted(firing, key=lambda r: (
        0 if r.get("severity") == "page" else 1, str(r.get("rule"))))
    worst = firing[0]
    ev = [f"alerts: {len(firing)} fleet rule(s) firing: "
          f"{[r.get('rule') for r in firing]}"]
    for r in firing[:4]:
        ev.append(f"  {r.get('rule')} [{r.get('severity', '?')}] "
                  f"value={r.get('value')} — "
                  f"{r.get('summary') or r.get('series', '')}")
    return Finding(SLO_BREACH, "slo-breach",
                   f"fleet alert {worst.get('rule')!r} is firing "
                   f"({worst.get('severity', '?')}) with no structural "
                   f"pathology matched",
                   confidence=0.7, evidence=ev,
                   details={"rules": [r.get("rule") for r in firing],
                            "worst": worst.get("rule")})


@_rule
def _healthy(b: Dict[str, Any]) -> Optional[Finding]:
    fleet = (b.get("ledger") or {}).get("fleet") or {}
    gp = fleet.get("goodput_fraction")
    ev = [f"queue depth {len(_queued(b))}, "
          f"{int(b.get('grants_total', 0) or 0)} grant(s), "
          f"{int(b.get('preemptions_total', 0) or 0)} preemption(s)"]
    if gp is not None:
        ev.append(f"ledger: fleet goodput_fraction = {float(gp):.2f} "
                  f"over {fleet.get('held_chip_s', 0)} chip-seconds "
                  f"held")
    return Finding(FLEET_HEALTHY, "healthy",
                   "no fleet-level pathology above threshold",
                   confidence=0.5, evidence=ev)


def run_rules(bundle: Dict[str, Any]) -> List[Finding]:
    """All findings, verdict candidate first (precedence, then
    confidence). A broken rule downgrades to absent — diagnosis must
    degrade, never die (the daemon calls this on its tick path)."""
    findings: List[Finding] = []
    for fn in _RULES:
        try:
            f = fn(bundle)
        except Exception:  # noqa: BLE001 — degrade, never die
            log.exception("fleet diagnosis rule %s failed",
                          getattr(fn, "__name__", "?"))
            continue
        if f is not None:
            findings.append(f)
    prec = {c: i for i, c in enumerate(CATEGORY_PRECEDENCE)}
    findings.sort(key=lambda f: (prec.get(f.category, len(prec)),
                                 -f.confidence))
    return findings


def build_incident(bundle: Dict[str, Any]) -> Dict[str, Any]:
    findings = run_rules(bundle)
    verdict = findings[0] if findings else Finding(
        FLEET_HEALTHY, "none", "no findings", confidence=0.0)
    # An alert firing at verdict time is corroborating evidence for a
    # structural verdict: boost its confidence and fold the rule names
    # in, so "the health ledger cordoned the slice AND goodput-slo was
    # firing" reads as one story, not two.
    firing = _firing_alerts(bundle)
    if firing and verdict.category not in (SLO_BREACH, FLEET_HEALTHY):
        verdict.confidence = min(0.99, verdict.confidence + 0.1)
        verdict.evidence.append(
            f"alerts: {[r.get('rule') for r in firing]} firing at "
            f"verdict time (corroborating)")
    fleet = (bundle.get("ledger") or {}).get("fleet") or {}
    return {
        "schema": INCIDENT_SCHEMA,
        "generated_ms": int(time.time() * 1000),
        "fleet_dir": bundle.get("fleet_dir", ""),
        "verdict": verdict.to_dict(),
        "findings": [f.to_dict() for f in findings],
        "goodput_fraction": fleet.get("goodput_fraction"),
        "alerts_firing": [r.get("rule") for r in firing],
        "queue_depth": len(_queued(bundle)),
        "grants_total": int(bundle.get("grants_total", 0) or 0),
        "preemptions_total": int(bundle.get("preemptions_total", 0)
                                 or 0),
    }


def save_incident(fleet_dir: str, doc: Dict[str, Any]) -> None:
    """Atomic replace — readers see a whole document or the previous
    one, the incident.json discipline."""
    from tony_tpu.utils.durable import atomic_write

    atomic_write(os.path.join(fleet_dir, constants.FLEET_INCIDENT_FILE),
                 json.dumps(doc, indent=1, sort_keys=True
                            ).encode("utf-8"))


def load_incident(fleet_dir: str) -> Optional[Dict[str, Any]]:
    try:
        with open(os.path.join(fleet_dir,
                               constants.FLEET_INCIDENT_FILE),
                  encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


def bundle_from_dir(fleet_dir: str,
                    now_ms: Optional[int] = None) -> Dict[str, Any]:
    """Rebuild the diagnosis bundle OFFLINE from a fleet dir — the
    shared timeline fold (fleet/timeline.py) + ledger fold + the
    replayed decision history; works on a dir copied off a dead host,
    no daemon needed."""
    from tony_tpu.fleet import journal as fjournal
    from tony_tpu.fleet import ledger as fledger
    from tony_tpu.fleet import timeline as ftimeline

    tl = ftimeline.load(fleet_dir)
    st = tl.state
    now = int(now_ms or time.time() * 1000)
    led = fledger.fold_fleet_dir(fleet_dir, now_ms=now,
                                 timeline=tl)
    queue: List[Dict[str, Any]] = []
    used: Dict[str, int] = {}
    for fold in st.jobs.values():
        if fold.state == "QUEUED":
            queue.append({
                "job": fold.job_id, "tenant": fold.tenant,
                "priority": fold.priority,
                "hosts": fold.hosts_requested,
                "wait_s": max(0.0, (now - fold.submitted_ms) / 1000.0)
                if fold.submitted_ms else 0.0,
                "last_decision": fold.decisions[-1]
                if fold.decisions else {}})
        elif fold.state not in fjournal.TERMINAL_STATES \
                and fold.hosts:
            used[fold.tenant] = used.get(fold.tenant, 0) + fold.hosts
    # preemption counts and the alert fold come from the timeline's raw
    # record prefix (the job fold keeps only the final placement)
    grants = len(tl.grant_waits)
    preempts = tl.preemptions_total
    preempts_per_job = dict(tl.preempts_per_job)
    alert_last = tl.alert_last
    grant_waits = tl.grant_waits
    median = grant_waits[len(grant_waits) // 2] if grant_waits else 0.0
    pool_dir = ""
    for fold in st.jobs.values():
        pool_dir = pool_dir or fold.conf.get("tony.pool.dir", "")
    # health fold: st.health is last-wins per host, so a host whose
    # final record is "healthy" has already been re-admitted.
    cordoned: List[Dict[str, Any]] = []
    for host in sorted(st.health):
        rec = st.health[host]
        if rec.get("state") not in ("quarantined", "probation"):
            continue
        cordoned.append({
            "host": host, "slice": rec.get("slice"),
            "state": rec.get("state"), "score": rec.get("score"),
            "manual": bool(rec.get("manual")),
            "reason": rec.get("reason", ""),
            "evidence": list(rec.get("evidence") or [])})
    sick = sorted({r["slice"] for r in cordoned
                   if str(r.get("reason", "")).startswith("sick slice")
                   and r.get("slice") is not None})
    return {
        "fleet_dir": fleet_dir,
        "quotas": dict(st.quotas), "tenants_used": used, "queue": queue,
        "median_grant_wait_s": round(median, 3),
        "grants_total": grants, "preemptions_total": preempts,
        "preempts_per_job": preempts_per_job,
        "ledger": {"tenants": led.get("tenants", {}),
                   "fleet": led.get("fleet", {})},
        "pool_dir": pool_dir,
        "health": {"enabled": bool(st.health),
                   "cordoned": cordoned, "sick_slices": sick},
        # Replayed REC_FLEET_ALERT fold: last-wins state per rule, so
        # the offline verdict sees exactly what was firing when the
        # daemon last wrote (severity/value from the raw record).
        "alerts": [{"rule": rule, "state": state,
                    "severity": alert_last.get(rule, {}).get(
                        "severity", "?"),
                    "value": alert_last.get(rule, {}).get("value"),
                    "summary": alert_last.get(rule, {}).get(
                        "summary", "")}
                   for rule, state in sorted(st.alerts.items())
                   if state == "firing"],
    }


def offline_explain(fleet_dir: str, job_id: str) -> Dict[str, Any]:
    """`fleet explain` without a daemon: rebuild the job's hold
    timeline from the replayed REC_FLEET_DECISION records (via the
    shared fleet/timeline.py fold) — the same response shape as the
    fleet.explain RPC."""
    from tony_tpu.fleet import timeline as ftimeline

    st = ftimeline.load(fleet_dir).state
    fold = st.jobs.get(job_id)
    if fold is None:
        return {"ok": False,
                "message": f"unknown job {job_id!r} in the journal "
                           f"under {fleet_dir}"}
    milestones: List[Dict[str, Any]] = [
        {"ts_ms": fold.submitted_ms,
         "what": f"submitted by tenant {fold.tenant!r} (priority "
                 f"{fold.priority}, {fold.hosts_requested} host(s))"}]
    if fold.granted_ms:
        milestones.append({"ts_ms": fold.granted_ms,
                           "what": f"granted {fold.hosts or '?'} "
                                   f"host(s)"})
    for ts, hosts in fold.host_events[1:]:
        milestones.append({"ts_ms": ts,
                           "what": f"resized to {hosts} host(s)"})
    if fold.finished_ms:
        milestones.append({"ts_ms": fold.finished_ms,
                           "what": f"finished {fold.state}"})
    return {"ok": True, "job": job_id, "state": fold.state,
            "tenant": fold.tenant, "app_id": fold.app_id,
            "decisions": list(fold.decisions),
            # Decision.blocking/free threaded through as attributed
            # hold seconds: which jobs blocked this one, under which
            # hold kind, for how long — the citation `fleet whatif`
            # diffs against when a counterfactual removes a hold.
            "holds": ftimeline.holds_summary(ftimeline.hold_intervals(
                fold.decisions, granted_ms=fold.granted_ms,
                finished_ms=fold.finished_ms,
                now_ms=int(time.time() * 1000),
                hosts=fold.hosts_requested)),
            "milestones": milestones, "offline": True}


def render_explain(doc: Dict[str, Any]) -> str:
    """The causal hold timeline, human-readable: decisions and
    milestones merged in time order, blockers named per hold."""
    import datetime

    def hhmmss(ts_ms: int) -> str:
        if not ts_ms:
            return "--:--:--.---"
        dt = datetime.datetime.fromtimestamp(ts_ms / 1000.0)
        return dt.strftime("%H:%M:%S.") + f"{ts_ms % 1000:03d}"

    rows: List[Dict[str, Any]] = []
    for m in doc.get("milestones", []):
        rows.append({"ts_ms": int(m.get("ts_ms", 0) or 0),
                     "line": m.get("what", "?"), "blocking": []})
    for d in doc.get("decisions", []):
        rows.append({"ts_ms": int(d.get("ts_ms", 0) or 0),
                     "line": f"[{d.get('action', '?')}] "
                             f"{d.get('reason', '?')}",
                     "blocking": d.get("blocking") or []})
    rows.sort(key=lambda r: r["ts_ms"])
    out = [f"{doc.get('job', '?')} (tenant {doc.get('tenant', '?')}) "
           f"— {doc.get('state', '?')}"
           + (f"  app={doc['app_id']}" if doc.get("app_id") else "")
           + ("  [offline: journal replay]" if doc.get("offline")
              else "")]
    if not rows:
        out.append("  (no recorded decisions — the job was never held)")
    for r in rows:
        out.append(f"  {hhmmss(r['ts_ms'])}  {r['line']}")
        if r["blocking"]:
            out.append(f"  {'':14}blocking: "
                       f"{', '.join(str(b) for b in r['blocking'])}")
    holds = doc.get("holds") or {}
    if holds:
        parts = []
        for kind in sorted(holds):
            h = holds[kind]
            cite = f" (blocking: {', '.join(h['blocking'])})" \
                if h.get("blocking") else ""
            free = f", {h['free']} free" \
                if kind == "fragmentation" else ""
            parts.append(f"{kind} {h['seconds']}s{free}{cite}")
        out.append(f"  held: {'; '.join(parts)}")
    return "\n".join(out)


def render_text(doc: Dict[str, Any]) -> str:
    v = doc.get("verdict") or {}
    lines = [f"fleet verdict: {v.get('category', '?')}  "
             f"(confidence {v.get('confidence', 0)})",
             f"  {v.get('summary', '')}",
             f"  advice: {v.get('advice', '')}"]
    for e in v.get("evidence", []):
        lines.append(f"  evidence: {e}")
    others = [f for f in doc.get("findings", [])
              if f.get("rule") != v.get("rule")]
    for f in others:
        lines.append(f"  also: [{f.get('category')}] "
                     f"{f.get('summary')}")
    gp = doc.get("goodput_fraction")
    if gp is not None:
        lines.append(f"  fleet goodput: {float(gp):.1%}")
    if doc.get("alerts_firing"):
        lines.append(f"  alerts firing: "
                     f"{', '.join(doc['alerts_firing'])}")
    return "\n".join(lines)
