"""Thin fleet RPC client over the fleet address file.

The fleet-side twin of ``pool.PoolClient``: resolve ``fleet.addr`` in
the fleet dir, dial the daemon over the ordinary token-authed RPC plane,
and carry the daemon's journaled generation on every frame — a zombie
daemon superseded by a ``--recover`` restart fences itself out of the
conversation (rpc/wire.py StaleGenerationError) instead of accepting
submissions into a dead queue.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

from tony_tpu import constants


class FleetClientError(RuntimeError):
    """The daemon is absent/unreachable or answered malformed — callers
    surface this to the operator (there is no cold-path fallback: with
    no fleet there is nowhere to queue)."""


def _read_json(path: str) -> Optional[dict]:
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        return data if isinstance(data, dict) else None
    except (OSError, ValueError):
        return None


class FleetClient:
    def __init__(self, fleet_dir: str):
        self.fleet_dir = os.path.abspath(os.path.expanduser(fleet_dir))
        self._rpc: Optional[Any] = None

    def _client(self) -> Any:
        if self._rpc is None:
            addr = _read_json(os.path.join(self.fleet_dir,
                                           constants.FLEET_ADDR_FILE))
            if not addr:
                raise FleetClientError(
                    f"no fleet daemon running under {self.fleet_dir} "
                    f"(start one with `tony-tpu fleet start`)")
            from tony_tpu.rpc.wire import RpcClient

            self._rpc = RpcClient(
                addr["host"], int(addr["port"]),
                token=addr.get("token") or None,
                generation=int(addr.get("generation", 0) or 0),
                max_retries=2, retry_sleep_s=0.2,
                connect_timeout_s=3.0, call_timeout_s=30.0, peer="fleet")
        return self._rpc

    def call(self, method: str, **args: Any) -> Any:
        try:
            return self._client().call(method, **args)
        except FleetClientError:
            raise
        except Exception as e:  # noqa: BLE001 — normalize transport errors
            self.close()
            raise FleetClientError(
                f"fleet rpc {method} failed: {e}") from e

    def submit(self, tenant: str, hosts: int, priority: int = 0,
               min_hosts: int = 0, model: str = "",
               conf: Optional[Dict[str, str]] = None) -> dict:
        res = self.call("fleet.submit", tenant=tenant, hosts=int(hosts),
                        priority=int(priority),
                        min_hosts=int(min_hosts), model=model,
                        conf=dict(conf or {}))
        if not isinstance(res, dict):
            raise FleetClientError(f"malformed submit response: {res!r}")
        return res

    def status(self) -> dict:
        res = self.call("fleet.status")
        if not isinstance(res, dict):
            raise FleetClientError(f"malformed status response: {res!r}")
        return res

    def cancel(self, job: str) -> dict:
        res = self.call("fleet.cancel", job=job)
        if not isinstance(res, dict):
            raise FleetClientError(f"malformed cancel response: {res!r}")
        return res

    def explain(self, job: str) -> dict:
        """The scheduler decision explainer: the job's causal hold
        timeline (reason transitions with blockers named) plus its
        grant/resize/finish milestones."""
        res = self.call("fleet.explain", job=job)
        if not isinstance(res, dict):
            raise FleetClientError(
                f"malformed explain response: {res!r}")
        return res

    def migrate(self, job: str, target: int) -> dict:
        """Operator-initiated live move of a running fleet job to
        another slice (defrag by hand, pre-maintenance evacuation)."""
        res = self.call("fleet.migrate", job=job, target=int(target))
        if not isinstance(res, dict):
            raise FleetClientError(
                f"malformed migrate response: {res!r}")
        return res

    def cordon(self, host: str, reason: str = "") -> dict:
        """Operator cordon: pull one host out of the placement pool
        (pre-maintenance, suspected hardware). Manual cordons never
        auto-expire — close them with uncordon."""
        res = self.call("fleet.cordon", host=host, reason=reason)
        if not isinstance(res, dict):
            raise FleetClientError(
                f"malformed cordon response: {res!r}")
        return res

    def uncordon(self, host: str) -> dict:
        res = self.call("fleet.uncordon", host=host)
        if not isinstance(res, dict):
            raise FleetClientError(
                f"malformed uncordon response: {res!r}")
        return res

    def health(self) -> dict:
        """The host-health ledger: per-host state/score/evidence rows,
        the current cordon set and any sick slices."""
        res = self.call("fleet.health")
        if not isinstance(res, dict):
            raise FleetClientError(
                f"malformed health response: {res!r}")
        return res

    def alerts(self) -> dict:
        """The fleet-scope alert engine: per-rule state machine rows
        plus the degrade flag (evaluation disabled after a fault)."""
        res = self.call("fleet.alerts")
        if not isinstance(res, dict):
            raise FleetClientError(
                f"malformed alerts response: {res!r}")
        return res

    def prom(self) -> str:
        """Live Prometheus exposition from the daemon's own registry
        (the file under the fleet dir refreshes only on the export
        cadence)."""
        res = self.call("fleet.prom")
        if not isinstance(res, dict) or "text" not in res:
            raise FleetClientError(
                f"malformed prom response: {res!r}")
        return str(res["text"])

    def stop(self) -> None:
        self.call("fleet.stop")

    def close(self) -> None:
        if self._rpc is not None:
            try:
                self._rpc.close()
            except Exception:  # noqa: BLE001
                pass
            self._rpc = None
