"""tony-tpu: a TPU-native framework for orchestrating distributed deep-learning jobs.

tony-tpu fills the role the reference framework (TonY — see /root/reference,
``README.md``) fills for Hadoop/YARN clusters, re-designed from scratch for TPU
hardware and the JAX/XLA execution model:

- A **job coordinator** (the ApplicationMaster analogue,
  reference ``tony-core/src/main/java/com/linkedin/tony/ApplicationMaster.java``)
  gang-schedules jobtypes over a slice inventory, runs the cluster-spec
  rendezvous barrier, monitors heartbeats and applies failure policy.
- A **task executor** (reference ``TaskExecutor.java``) supervises one user
  process per task, wiring the framework-specific environment contract
  (JAX coordination service, TF_CONFIG, torch rendezvous, DMLC_*).
- A **client library + CLI** (reference ``TonyClient.java``,
  ``tony-cli/``) merges layered configs into a frozen artifact, validates
  resource quotas, submits, and mirrors task state to listeners.
- A **parallelism library** (new work — absent from the reference, see
  SURVEY.md §2.3) owns what TonY delegated to user frameworks: device meshes,
  DP/FSDP/TP/PP/EP and sequence/context parallelism with ring attention,
  implemented with jax.sharding / shard_map / pallas.

Unlike the reference, the data plane and the orchestration plane meet here:
XLA collectives over ICI/DCN are the communication backend, bootstrapped by
the coordinator's rendezvous (replacing four env-var dialects with one).
"""

__version__ = "0.1.0"

# With TONY_LOCK_SANITIZER=1 in the environment, arm the lock sanitizer
# BEFORE any tony_tpu module allocates a lock (telemetry below has
# module-level locks), so executor/coordinator/pool subprocesses of a
# sanitized run join the lock-order/hazard verdict; no-op — one env read
# — everywhere else.
from tony_tpu.devtools import sanitizer as _sanitizer  # noqa: E402

_sanitizer.maybe_enable_from_env()

# Same contract for the data-race detector (TONY_RACE_DETECTOR=1,
# devtools/race.py): it must arm BEFORE the @guarded control-plane
# classes are defined (decoration is the instrumentation point) and
# before any thread starts, so subprocesses of an armed run join the
# suite-wide race verdict; no-op — one env read — everywhere else.
from tony_tpu.devtools import race as _race  # noqa: E402

_race.maybe_enable_from_env()

from tony_tpu import constants  # noqa: F401
from tony_tpu.conf.config import TonyTpuConfig  # noqa: F401

# Inside a task (TONY_METRICS_FILE set by the executor) a bare import is
# enough to start the HBM telemetry reporter; no-op everywhere else.
from tony_tpu import telemetry as _telemetry  # noqa: E402

_telemetry.maybe_start()

# Inside a task (TONY_STACKDUMP_SIGNAL set by the executor) the same bare
# import pre-registers the hung-task diagnostics handler: the coordinator's
# progress liveness can then get an all-thread stack dump out of a wedged
# user process before killing it; no-op everywhere else.
_telemetry.install_stack_dump_handler()

# Inside a task whose supervisor exported TONY_FAULTS, arm the fault
# harness for this process too (user scripts' checkpoint/storage calls are
# injection sites); no-op — one env read — everywhere else.
from tony_tpu import faults as _faults  # noqa: E402

_faults.install_from_env()

# Inside a multi-process CPU task (virtual-mesh gangs), select a working
# cross-process collectives backend before the first computation; no-op
# everywhere else. Deliberately NOT `from tony_tpu import compat` at module
# scope for the coordinator/CLI processes' sake — compat imports jax, and
# control-plane processes must not pay (or require) a jax import.
import os as _os  # noqa: E402

if int(_os.environ.get("JAX_NUM_PROCESSES", "1") or 1) > 1:
    from tony_tpu import compat as _compat  # noqa: E402

    _compat.configure_cpu_collectives()
