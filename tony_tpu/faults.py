"""Deterministic fault-injection harness: rehearse infra failure on purpose.

The reference proved its robustness story with env-hook faults compiled
into production code (``Constants.java:116-121``: AM crash, worker
termination, heartbeat misses, completion delay) — deterministic enough
to drive an E2E matrix (``TestTonyE2E.java``). This module generalizes
that idea into one conf-driven, seeded subsystem with injection sites
threaded through every layer that talks to unreliable infrastructure:

========================  =====================================================
site                      where it fires
========================  =====================================================
``rpc.connect``           RpcClient._connect, before the TCP connect
``rpc.send``              RpcClient.call, before a request frame is sent
``heartbeat``             Heartbeater loop (a firing skips that heartbeat)
``executor.spawn``        backend launch_task, before the process spawn
``storage.put``           Store.put_file via the retrying wrapper
``storage.get``           Store.get_file via the retrying wrapper
``checkpoint.save``       CheckpointManager.save, before the orbax call
``coordinator.crash``     Coordinator._monitor loop: hard os._exit(137) —
                          the SIGKILL shape that --recover must survive
``executor.reregister``   executor reconnect: drops a re-registration
                          attempt during coordinator-loss recovery
``user.hang``             telemetry.step_done: a firing silently drops the
                          step recording — heartbeats continue, progress
                          freezes (the hung-user-process shape)
``user.slow_step``        telemetry.step_done: a firing delays the step by
                          ``amt:`` seconds — one task's step rate skews
                          below the gang median (the straggler shape)
``rpc.slow``              RpcClient.call: a firing delays the request by
                          ``amt:`` seconds before it is sent — injected
                          control-plane latency that never drops a frame
                          (exercises trace spans + latency histograms)
``pool.lease``            backend warm-pool adoption, before the lease RPC
                          — the lease-refused/daemon-unreachable shape;
                          the backend must cold-spawn instead
``pool.stale``            backend warm-pool adoption, before the lease RPC
                          — simulates the daemon's stale-generation lease
                          refusal (a zombie epoch trying to lease); the
                          backend must cold-spawn instead
``pool.adopt``            backend warm-pool adoption, after a granted
                          lease — the leased-executor-dead-on-adoption
                          shape; the backend must discard the lease and
                          cold-spawn instead
``host.loss``             executor heartbeat loop: a firing SIGKILLs the
                          user process group and hard-exits the executor
                          (os._exit 137) — sudden whole-host death, the
                          shape elastic shrink-and-continue absorbs;
                          combine ``after:N``/``task:ID`` to fell one
                          deterministic virtual host mid-run
``resize.barrier``        coordinator elastic re-mesh, once per resize
                          after the new topology is applied — a failed
                          post-resize re-registration barrier; the resize
                          aborts INFRA_TRANSIENT into the retry machinery
``resize.remesh``         coordinator elastic re-mesh, once per resize
                          before the member set is rebuilt — a failed
                          topology application; same abort path
``profile.capture``       telemetry on-demand device capture, at the step
                          boundary that would arm jax.profiler — the
                          unsupported/failed-capture shape; the task
                          reports PROFILE_FAILED on the next beat and
                          training continues (capture must never kill or
                          stall the job)
``quant.probe``           ops/quant.py backend support probe for the
                          int8/fp8 matmul path — a firing simulates an
                          unsupported backend; the model must degrade to
                          bf16 with a one-time beacon warning, never
                          fail the job
``coord.slow-tick``       Coordinator._monitor loop: a firing stalls the
                          tick by ``amt:`` seconds before any per-tick
                          work — the overloaded-control-plane shape the
                          coordinator's own phase accounting must
                          surface (tick duration in ``top``, the
                          control-plane verdicts); the call counter is
                          monitor iterations
``fleet.grant``           fleet daemon grant application, after the
                          placement decision and the write-ahead grant
                          record but before the job spawn — the
                          unspawnable-grant shape; the job must stay
                          QUEUED and be retried, never lost
``fleet.preempt``         fleet daemon preempt-to-reclaim, before the
                          victim's elastic shrink RPC — the
                          unreachable-victim shape; the preemption (and
                          the grant waiting on the reclaimed hosts) is
                          retried on a later tick, the victim keeps
                          running
``fleet.ledger``          fleet goodput-ledger fold (reading a job's
                          span tree / perf.json / events into phase
                          accounting) — a firing simulates a corrupt
                          artifact; the fleet degrades to counters-only
                          with a one-time warning and the scheduler
                          tick never blocks or fails
``fleet.explain``         fleet decision-record journal write
                          (REC_FLEET_DECISION) — a firing simulates a
                          full/failed disk on the observability path;
                          the decision is still applied (ring + event),
                          one-time warning, scheduling unaffected
``ckpt.async-write``      checkpoint background writer, before the
                          serialized bytes are handed to orbax — the
                          failed-in-flight-async-save shape; the step
                          is NOT committed (no manifest), restore falls
                          back to the last committed step, training
                          continues
``migrate.snapshot``      coordinator migration, before the drained
                          gang's state is sealed for the move — the
                          failed-snapshot shape; the migration aborts
                          into the ordinary INFRA_TRANSIENT retry
                          ladder (never worse than a host loss)
``migrate.adopt``         coordinator migration, after the topology
                          moved but before destination executors
                          launch — the unadoptable-destination shape;
                          same abort path
``slice.preempt``         fleet slice-reclaim notice poll: a firing
                          marks one held slice as dying (the
                          queued-resource spot-reclaim shape) so the
                          fleet rehearses proactive migration off it
``rpc.partition``         RpcClient.call, per frame and per DIRECTION —
                          ``dir:c2s`` drops the request before it is
                          sent (the callee never sees it), ``dir:s2c``
                          drops the RESPONSE after the callee has
                          already processed the request (its side
                          effects land; the caller sees a reset and
                          retries) — the asymmetric-partition shape;
                          ``peer:NAME`` scopes the cut to one wire
                          (coordinator / pool / fleet)
``disk.full``             utils/durable AppendLog.append, before the
                          write — ENOSPC on the fsync'd journal append;
                          the writer must degrade LOUDLY (terminal
                          INFRA verdict / daemon stop), never silently
                          truncate, and ``--recover`` must replay the
                          committed prefix
``disk.torn``             utils/durable — AppendLog.append writes a
                          torn partial record then fails EIO, and
                          atomic_write drops the rename (old bytes
                          survive) — the power-cut-mid-write shape the
                          replay-of-prefix readers must absorb
``host.flaky``            fleet daemon health tick, per running job and
                          assigned host (``task:<host>`` pins it, e.g.
                          ``task:s0h2``) — a firing attributes an
                          INFRA_TRANSIENT failure to that host and
                          kills the job, the recurring-bad-hardware
                          shape the quarantine ledger must cordon
``health.probe``          fleet preflight probe (health.preflight_probe),
                          per probed host before a grant books it —
                          a firing simulates a host failing its port
                          bind / durable-write check; the grant must
                          self-repair by cordoning the host and
                          substituting a spare, never spawn on it
========================  =====================================================

Spec grammar (the value of ``tony.fault.<site>`` conf keys, or one
``;``-separated assignment list in the ``TONY_FAULTS`` env var):

- ``first:N``   — fire on the first N calls of the site (per process)
- ``at:K``      — fire on call K only (1-based)
- ``after:N``   — fire on every call past the first N (the freeze shape:
  progress that starts fine and then stops forever)
- ``every:N``   — fire on every Nth call
- ``p:X``       — fire with probability X, from a per-site RNG seeded
  with (seed, site) — the sequence of decisions is identical for a given
  seed, machine-independent
- ``prob:P``    — fire with probability P, decided by a stable hash of
  (seed, site, call-index): unlike ``p:X``'s sequential RNG the decision
  for call #N is a pure function of the seed — chaos schedules can
  predict, replay and SHRINK around it. Seed comes from the injector
  (``seed=N`` / ``tony.fault.seed``), defaulting to ``TONY_FAULT_SEED``
- ``session:S`` — additional filter: only fire when this process's
  ``TONY_SESSION_ID`` is S (lets a fault hit epoch 0 and spare the retry)
- ``task:T``    — additional filter: only fire when this process's
  ``TONY_TASK_ID`` is T (e.g. ``task:worker:1`` — slow ONE gang member)
- ``amt:X``     — payload for sites that take a magnitude (float,
  site-interpreted: ``user.slow_step`` reads it as seconds of delay)
- ``dir:D``     — additional filter for directional sites
  (``rpc.partition``): only fire when the call site reports direction D
  (``c2s`` = request frames, ``s2c`` = response frames)
- ``peer:NAME`` — additional filter for labelled wires: only fire when
  the call site reports peer NAME (the RpcClient's ``peer`` label:
  ``coordinator``, ``pool``, ``fleet``)

Tokens combine with ``,``: ``p:0.5,session:0``. Example conf:

    tony.fault.seed = 7
    tony.fault.rpc-send = first:2
    tony.fault.storage-get = p:0.3,session:0

Plumbing: the coordinator installs from its conf and forwards the same
spec to every executor via the ``TONY_FAULTS`` env var (executors must be
able to inject into the storage fetch of the very config that carries the
keys); the client installs from conf at submit for its staging I/O.

Zero overhead when disabled: ``fire(site)`` is a module-global None check
— no dict lookups, no RNG, nothing to configure away in production.
"""

from __future__ import annotations

import hashlib
import logging
import os
import random
import threading
from typing import Any, Dict, List, Optional, Tuple

log = logging.getLogger(__name__)

#: env var carrying the serialized spec into executor/user processes
FAULTS_ENV = "TONY_FAULTS"

#: env var supplying the DEFAULT injector seed (chaos schedules export it
#: so ``prob:P`` decisions replay bit-identically in every child process;
#: an explicit ``seed=N`` token / ``tony.fault.seed`` conf still wins)
FAULT_SEED_ENV = "TONY_FAULT_SEED"

#: the canonical site names (kept in lockstep with the conf keys in
#: tony_tpu/conf/keys.py: ``tony.fault.<site with . -> ->``)
SITES = ("rpc.connect", "rpc.send", "rpc.slow", "heartbeat",
         "executor.spawn", "storage.put", "storage.get", "checkpoint.save",
         "coordinator.crash", "executor.reregister",
         "user.hang", "user.slow_step",
         "pool.lease", "pool.stale", "pool.adopt",
         "host.loss", "resize.barrier", "resize.remesh",
         "profile.capture", "quant.probe", "coord.slow-tick",
         "fleet.grant", "fleet.preempt", "fleet.ledger", "fleet.explain",
         "ckpt.async-write", "migrate.snapshot", "migrate.adopt",
         "slice.preempt", "rpc.partition", "disk.full", "disk.torn",
         "host.flaky", "health.probe", "alerts.eval")


class InjectedFault(ConnectionError):
    """Raised by injection sites that simulate transport/IO failure.

    Subclasses ConnectionError (hence OSError) on purpose: the production
    retry paths — RPC reconnect, storage transfer retry — must treat an
    injected fault EXACTLY like a real reset, with no fault-harness
    special-casing in the code under test.
    """

    def __init__(self, site: str, call_no: int) -> None:
        super().__init__(f"injected fault at {site} (call #{call_no})")
        self.site = site
        self.call_no = call_no


class _SiteRule:
    """Parsed spec + deterministic per-site decision state."""

    def __init__(self, site: str, spec: str, seed: int) -> None:
        self.site = site
        self.spec = spec
        self.first = 0
        self.at = 0
        self.after = 0
        self.every = 0
        self.p = 0.0
        self.prob = 0.0
        self.amount = 0.0
        self.session: Optional[int] = None
        self.task: Optional[str] = None
        self.direction: Optional[str] = None
        self.peer: Optional[str] = None
        for token in spec.split(","):
            token = token.strip()
            if not token:
                continue
            # Partition on the FIRST separator only: the task filter's
            # value legitimately contains ':' ("task:worker:1").
            key, sep, value = token.replace("=", ":", 1).partition(":")
            if not sep:
                raise ValueError(
                    f"fault spec token {token!r} for {site!r} needs "
                    f"key:value (one of first/at/after/every/p/prob/amt/"
                    f"session/task/dir/peer)")
            key = key.strip().lower()
            value = value.strip()
            try:
                if key == "first":
                    self.first = int(value)
                elif key == "at":
                    self.at = int(value)
                elif key == "after":
                    self.after = int(value)
                elif key == "every":
                    self.every = int(value)
                elif key == "p":
                    self.p = float(value)
                elif key == "prob":
                    self.prob = float(value)
                elif key == "amt":
                    self.amount = float(value)
                elif key == "session":
                    self.session = int(value)
                elif key == "task":
                    self.task = value
                elif key == "dir":
                    if value not in ("c2s", "s2c"):
                        raise ValueError(
                            f"dir: must be c2s or s2c, got {value!r}")
                    self.direction = value
                elif key == "peer":
                    self.peer = value
                else:
                    raise ValueError(f"unknown fault spec key {key!r}")
            except ValueError as e:
                raise ValueError(
                    f"bad fault spec {spec!r} for {site!r}: {e}") from e
        # Per-site RNG seeded by (seed, site): decision sequences are
        # reproducible and independent across sites.
        self._rng = random.Random(f"{seed}:{site}")
        self._seed = seed
        self._calls = 0
        self._lock = threading.Lock()

    def _hash_draw(self, n: int) -> float:
        """Stable uniform [0, 1) for call #n: a pure function of
        (seed, site, n) — unlike the sequential ``p:`` RNG, the decision
        for a given call index is independent of every other call, so a
        shrunk schedule keeps the surviving injections' decisions."""
        h = hashlib.sha256(
            f"{self._seed}:{self.site}:{n}".encode()).digest()
        return int.from_bytes(h[:8], "big") / float(1 << 64)

    def decide(self, direction: Optional[str] = None,
               peer: Optional[str] = None,
               task_id: Optional[str] = None) -> Tuple[bool, int]:
        """(fire?, call number) — one deterministic decision per call.

        ``dir:``/``peer:`` filters are scope, not outcome: an
        out-of-scope frame does NOT consume a call index, so
        ``dir:s2c,at:3`` means "the 3rd RESPONSE frame", not "call 3 if
        it happens to be a response".

        ``task_id`` lets IN-PROCESS callers (the virtual gang, where
        every task shares one process) name the task on whose behalf the
        site is polled; subprocess executors keep the env-derived
        identity. ``task:*`` matches every task — the correlated-loss
        spec (``host.loss=task:*,first:2`` kills the first two beats to
        poll, i.e. two DIFFERENT hosts near-simultaneously)."""
        if self.direction is not None and direction != self.direction:
            with self._lock:
                return False, self._calls
        if self.peer is not None and peer != self.peer:
            with self._lock:
                return False, self._calls
        # The task filter is scope too — WHEN the caller names the task
        # in-process (``task:worker:1,at:3`` = that task's 3rd poll, not
        # "poll 3 if it happens to be hers"). Subprocess executors keep
        # the env-derived post-counter check: their counter is already
        # per-process, so the filter always matches locally.
        if self.task is not None and task_id is not None:
            if self.task != "*" and task_id != self.task:
                with self._lock:
                    return False, self._calls
        with self._lock:
            self._calls += 1
            n = self._calls
            # Draw EVERY call so the p-sequence depends only on the call
            # index, not on which other tokens matched before it.
            draw = self._rng.random()
        if self.session is not None:
            env_session = int(os.environ.get("TONY_SESSION_ID", "0") or 0)
            if env_session != self.session:
                return False, n
        if self.task is not None and task_id is None:
            if self.task != "*" and \
                    os.environ.get("TONY_TASK_ID", "") != self.task:
                return False, n
        if self.first and n <= self.first:
            return True, n
        if self.at and n == self.at:
            return True, n
        if self.after and n > self.after:
            return True, n
        if self.every and n % self.every == 0:
            return True, n
        if self.p and draw < self.p:
            return True, n
        if self.prob and self._hash_draw(n) < self.prob:
            return True, n
        return False, n


class FaultInjector:
    def __init__(self, rules: Dict[str, str], seed: int = 0) -> None:
        unknown = set(rules) - set(SITES)
        if unknown:
            raise ValueError(
                f"unknown fault site(s) {sorted(unknown)}; known: "
                f"{list(SITES)}")
        self.seed = seed
        self.rules = {site: _SiteRule(site, spec, seed)
                      for site, spec in rules.items() if spec}

    def fire(self, site: str, task_id: Optional[str] = None) -> bool:
        rule = self.rules.get(site)
        if rule is None:
            return False
        fired, call_no = rule.decide(task_id=task_id)
        if fired:
            log.warning("FAULT INJECTED at %s (call #%d, spec %r)",
                        site, call_no, rule.spec)
        return fired

    def fire_amount(self, site: str) -> Optional[float]:
        """Like fire(), but returns the rule's ``amt:`` payload when the
        site fires (None otherwise) — for magnitude-style sites
        (user.slow_step: seconds of injected delay per fired step)."""
        rule = self.rules.get(site)
        if rule is None:
            return None
        fired, call_no = rule.decide()
        if not fired:
            return None
        log.warning("FAULT INJECTED at %s (call #%d, spec %r, amt %g)",
                    site, call_no, rule.spec, rule.amount)
        return rule.amount

    def check(self, site: str) -> None:
        """Raise InjectedFault when the site fires (transport-style sites)."""
        rule = self.rules.get(site)
        if rule is None:
            return
        fired, call_no = rule.decide()
        if fired:
            log.warning("FAULT INJECTED at %s (call #%d, spec %r)",
                        site, call_no, rule.spec)
            raise InjectedFault(site, call_no)

    def check_partition(self, site: str, direction: str, peer: str) -> None:
        """Directional ``check``: the wire layer reports which way the
        frame is travelling (``c2s``/``s2c``) and over which labelled
        wire; a rule's ``dir:``/``peer:`` filters scope the cut."""
        rule = self.rules.get(site)
        if rule is None:
            return
        fired, call_no = rule.decide(direction=direction, peer=peer)
        if fired:
            log.warning("FAULT INJECTED at %s (call #%d, dir %s, peer %s, "
                        "spec %r)", site, call_no, direction, peer,
                        rule.spec)
            raise InjectedFault(site, call_no)

    def to_env_value(self) -> str:
        """Serialize for the TONY_FAULTS env passthrough."""
        parts = [f"seed={self.seed}"]
        parts += [f"{site}={rule.spec}"
                  for site, rule in sorted(self.rules.items())]
        return ";".join(parts)


#: THE hot-path switch. None = disabled = zero overhead beyond one global
#: read; production code never pays for the harness it isn't using.
_active: Optional[FaultInjector] = None


def active() -> Optional[FaultInjector]:
    return _active


def fire(site: str, task_id: Optional[str] = None) -> bool:
    """Did the site fire? (bool-style sites: heartbeat skip). In-process
    multi-task callers pass ``task_id`` for the ``task:`` filter;
    subprocess callers rely on the TONY_TASK_ID env identity."""
    inj = _active
    return inj is not None and inj.fire(site, task_id=task_id)


def fire_amount(site: str) -> Optional[float]:
    """Did the site fire, and with what ``amt:`` payload? None = no
    (magnitude-style sites: user.slow_step)."""
    inj = _active
    return inj.fire_amount(site) if inj is not None else None


def check(site: str) -> None:
    """Raise InjectedFault if the site fires (exception-style sites)."""
    inj = _active
    if inj is not None:
        inj.check(site)


def check_partition(site: str, direction: str, peer: str) -> None:
    """Raise InjectedFault if the directional site fires for this
    (direction, peer) — the asymmetric-partition hook (rpc.partition)."""
    inj = _active
    if inj is not None:
        inj.check_partition(site, direction, peer)


def env_seed(default: int = 0) -> int:
    """The ambient injector seed: TONY_FAULT_SEED when set (chaos runs
    export it), else ``default``."""
    raw = os.environ.get(FAULT_SEED_ENV, "")
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        log.warning("ignoring non-integer %s=%r", FAULT_SEED_ENV, raw)
        return default


def install(injector: Optional[FaultInjector]) -> None:
    global _active
    _active = injector
    if injector is not None and injector.rules:
        from tony_tpu import retry as _retry

        # Seeded faults deserve seeded backoff jitter: the full schedule
        # of a rehearsed failure is then reproducible end to end.
        _retry.seed_default_rng(injector.seed)
        log.warning("fault injection ACTIVE: %s",
                    injector.to_env_value())


def uninstall() -> None:
    install(None)


def parse_spec(spec: str, default_seed: Optional[int] = None) -> "FaultInjector":
    """Parse the serialized ``site=spec;site=spec;seed=N`` form. With no
    explicit default, the seed falls back to TONY_FAULT_SEED then 0."""
    rules: Dict[str, str] = {}
    seed = env_seed(0) if default_seed is None else default_seed
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        key, sep, value = part.partition("=")
        if not sep:
            raise ValueError(f"bad TONY_FAULTS entry {part!r} "
                             f"(need site=spec)")
        key = key.strip()
        if key == "seed":
            seed = int(value)
        else:
            rules[key] = value.strip()
    return FaultInjector(rules, seed=seed)


def install_from_env() -> bool:
    """Executor/user-process path: TONY_FAULTS beats everything (it must —
    the faults may target the storage fetch of the config itself)."""
    spec = os.environ.get(FAULTS_ENV, "")
    if not spec:
        return False
    install(parse_spec(spec))
    return True


def install_from_conf(conf: Any) -> bool:
    """Coordinator/client path: read ``tony.fault.*`` keys. Returns True
    iff any site is configured (callers then export TONY_FAULTS)."""
    from tony_tpu.conf import keys as K

    rules: Dict[str, str] = {}
    for site in SITES:
        spec = str(conf.get(K.fault_key(site), "") or "")
        if spec:
            rules[site] = spec
    if not rules:
        return False
    install(FaultInjector(rules, seed=conf.get_int(K.FAULT_SEED,
                                                   env_seed(0))))
    return True


def env_passthrough() -> Dict[str, str]:
    """Env vars a supervisor exports so child processes inherit the active
    injection config (empty when disabled)."""
    inj = _active
    if inj is None or not inj.rules:
        return {}
    return {FAULTS_ENV: inj.to_env_value()}
