"""CLI: submit and inspect jobs.

Reference model: ``tony-cli`` — ``ClusterSubmitter`` (stage + delegate to the
client with a kill-on-exit hook, :49-74), ``LocalSubmitter`` (zero-install
demo against an in-process cluster, :47-68). The history subcommand covers
the portal's jobs-index view for terminals (``tony-portal/conf/routes:1``).

Usage:
    python -m tony_tpu.cli submit --conf-file job.yaml [--conf k=v ...]
    python -m tony_tpu.cli submit --executable train.py --instances 2
    python -m tony_tpu.cli history [--history-root DIR]
    python -m tony_tpu.cli events <app_id>
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import sys
import time
from typing import List, Optional

from tony_tpu import faults as _faults
from tony_tpu.client import TaskUpdateListener, TonyTpuClient
from tony_tpu.conf import keys as K


class _LogListener(TaskUpdateListener):
    def on_application_id_received(self, app_id: str) -> None:
        print(f"submitted application {app_id}")

    def on_task_infos_updated(self, task_infos) -> None:
        states = {}
        for t in task_infos:
            states.setdefault(t.get("status", "?"), []).append(
                f"{t.get('name', '?')}:{t.get('index', '?')}")
        print("tasks:", "  ".join(
            f"{s}={','.join(ids)}" for s, ids in sorted(states.items())))

    def on_application_finished(self, status: str, report: dict) -> None:
        print(f"application finished: {status}")
        if report.get("failure_reason"):
            print(f"reason: {report['failure_reason']}")
        if report.get("failure_domain"):
            print(f"failure domain: {report['failure_domain']}")


def _cmd_submit(args: argparse.Namespace) -> int:
    overrides = list(args.conf or [])
    if args.executable:
        overrides.append(f"{K.APPLICATION_EXECUTABLE}={args.executable}")
    if args.task_params:
        overrides.append(f"{K.APPLICATION_TASK_PARAMS}={args.task_params}")
    if args.src_dir:
        overrides.append(f"{K.SRC_DIR}={args.src_dir}")
    if args.instances is not None:
        overrides.append(f"tony.worker.instances={args.instances}")
    client = TonyTpuClient.from_args(config_file=args.conf_file,
                                     overrides=tuple(overrides),
                                     workdir=args.workdir)
    client.add_listener(_LogListener())

    # Kill-on-exit hook (reference ClusterSubmitter.java:69).
    def on_signal(signum, frame):
        print(f"signal {signum}: killing application", file=sys.stderr)
        client.force_kill()
        sys.exit(130)

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    return client.start()


def _cmd_notebook(args: argparse.Namespace) -> int:
    from tony_tpu.conf.config import TonyTpuConfig
    from tony_tpu.notebook import submit_notebook

    conf = TonyTpuConfig.from_layers(config_file=args.conf_file,
                                     overrides=tuple(args.conf or []))
    return submit_notebook(conf, workdir=args.workdir,
                           command=args.command or "",
                           local_port=args.port)


def _default_workdir(arg):
    """Single source for the client workdir default (must match what
    submit used, or kill/history look in the wrong place)."""
    return arg or os.environ.get(
        "TONY_TPU_WORKDIR",
        os.path.join(os.path.expanduser("~"), ".tony-tpu"))


def _cmd_kill(args: argparse.Namespace) -> int:
    """Force-kill a running application by id (reference
    ``forceKillApplication`` TonyClient.java:959, as a standalone command:
    the coordinator's RPC endpoint is discovered from the job dir's
    address file, like the client does at submit)."""
    rpc = _coordinator_rpc(args.app_id, args.workdir)
    if rpc is None:
        print(f"no coordinator address for {args.app_id} under "
              f"{_default_workdir(args.workdir)} (wrong --workdir, or the "
              f"job already finished)", file=sys.stderr)
        return 1
    try:
        rpc.call("kill_application")
    except Exception as e:  # noqa: BLE001
        print(f"kill failed (coordinator gone?): {e}", file=sys.stderr)
        return 1
    print(f"kill signal sent to {args.app_id}")
    return 0


def _cmd_recover(args: argparse.Namespace) -> int:
    """Restart a crashed coordinator in-place with --recover: replay the
    job's write-ahead session journal, re-adopt the surviving executors,
    and block until the job finishes (the operator-facing face of
    coordinator crash recovery — docs/operations.md). Runs the
    coordinator IN this process so its exit code is the job's."""
    job_dir = os.path.join(_default_workdir(args.workdir), "jobs",
                           args.app_id)
    from tony_tpu import constants
    from tony_tpu.conf.config import TonyTpuConfig

    frozen = os.path.join(job_dir, constants.FINAL_CONFIG_FILE)
    if not os.path.exists(frozen):
        print(f"no frozen config for {args.app_id} under {job_dir} "
              f"(wrong --workdir?)", file=sys.stderr)
        return 1
    conf = TonyTpuConfig.load_final(frozen)
    history_root = args.history_root \
        or str(conf.get(K.HISTORY_LOCATION, "") or "") \
        or os.path.join(_default_workdir(args.workdir), "history")
    # Refuse cleanly when there is nothing to replay — better than the
    # coordinator failing after it already rebound the address file.
    journal_path = os.path.join(history_root,
                                constants.HISTORY_INTERMEDIATE,
                                args.app_id, constants.JOURNAL_FILE)
    if not os.path.exists(journal_path):
        print(f"no session journal at {journal_path} — the job was not "
              f"run with tony.coordinator.journal-enabled, or it already "
              f"finished (check `tony-tpu status {args.app_id}`)",
              file=sys.stderr)
        return 1
    from tony_tpu.coordinator.__main__ import main as coordinator_main

    print(f"recovering {args.app_id} from {journal_path}")
    return coordinator_main([
        "--conf", frozen,
        "--app-id", args.app_id,
        "--history-root", history_root,
        "--workdir", os.path.join(job_dir, "tasks"),
        "--addr-file", os.path.join(job_dir, "coordinator.addr"),
        "--user", os.environ.get("USER", "unknown"),
        "--recover",
    ])


def _coordinator_rpc(app_id: str, workdir: Optional[str]):
    """RpcClient for a RUNNING job's coordinator, from the job dir's
    address file (how kill/status reach a job after the submitting
    process is gone); None when the file is absent."""
    import json

    from tony_tpu.rpc.wire import RpcClient

    addr_file = os.path.join(_default_workdir(workdir), "jobs", app_id,
                             "coordinator.addr")
    if not os.path.exists(addr_file):
        return None
    with open(addr_file, encoding="utf-8") as f:
        addr = json.load(f)
    tls = None
    if addr.get("tls_cert"):
        from tony_tpu.rpc.wire import client_tls_context
        tls = client_tls_context(addr["tls_cert"])
    return RpcClient(addr["host"], addr["port"],
                     token=addr.get("token") or None,
                     max_retries=2, retry_sleep_s=0.5, tls=tls,
                     peer="coordinator")


def _cmd_resize(args: argparse.Namespace) -> int:
    """Elastic resize of a RUNNING job's gang (coordinator/elastic.py):
    shrink drains the survivors at a step barrier and re-meshes —
    releasing the highest indices — grow re-admits members through the
    same barrier. Requires tony.elastic.enabled on the job; refused
    below tony.elastic.min-tasks."""
    rpc = _coordinator_rpc(args.app_id, args.workdir)
    if rpc is None:
        print(f"no coordinator address for {args.app_id} under "
              f"{_default_workdir(args.workdir)} (job finished? wrong "
              f"--workdir?) — resize needs a live job", file=sys.stderr)
        return 1
    try:
        res = rpc.call("resize_application", size=args.size,
                       job=args.job or "")
    except Exception as e:  # noqa: BLE001
        print(f"resize failed (coordinator gone?): {e}", file=sys.stderr)
        return 1
    finally:
        rpc.close()
    if not isinstance(res, dict) or not res.get("ok"):
        msg = res.get("message", "refused") if isinstance(res, dict) \
            else str(res)
        print(f"resize refused: {msg}", file=sys.stderr)
        return 1
    print(res.get("message", "resize accepted"))
    print(f"members: {res.get('members')}")
    print(f"watch it land with `tony-tpu top {args.app_id}` "
          f"(gang=/mgen= columns) or `tony-tpu events {args.app_id}` "
          f"(GANG_RESIZED)")
    return 0


def _cmd_migrate(args: argparse.Namespace) -> int:
    """Live migration of a RUNNING job's gang to another slice
    (coordinator/migrate.py): fenced DRAIN at a step barrier → final
    durable saves → relaunch/adopt on the target → restore-with-reshard
    — a planned move with steps_lost==0, vs. the crash-shaped path a
    reclaim would force. Requires tony.elastic.enabled on the job."""
    rpc = _coordinator_rpc(args.app_id, args.workdir)
    if rpc is None:
        print(f"no coordinator address for {args.app_id} under "
              f"{_default_workdir(args.workdir)} (job finished? wrong "
              f"--workdir?) — migrate needs a live job", file=sys.stderr)
        return 1
    try:
        res = rpc.call("migrate_application", target=args.target,
                       job=args.job or "")
    except Exception as e:  # noqa: BLE001
        print(f"migrate failed (coordinator gone?): {e}",
              file=sys.stderr)
        return 1
    finally:
        rpc.close()
    if not isinstance(res, dict) or not res.get("ok"):
        msg = res.get("message", "refused") if isinstance(res, dict) \
            else str(res)
        print(f"migrate refused: {msg}", file=sys.stderr)
        return 1
    print(res.get("message", "migration accepted"))
    print(f"members: {res.get('members')}")
    print(f"route:   {res.get('source') or '(default pool)'} -> "
          f"{res.get('target')}")
    print(f"watch it land with `tony-tpu events {args.app_id}` "
          f"(GANG_MIGRATED) or `tony-tpu top {args.app_id}` "
          f"(mgen= column)")
    return 0


def _cmd_status(args: argparse.Namespace) -> int:
    """Live application report from a running job's coordinator
    (reference: the client's status poll surface, ``TonyClient.java:838``;
    the yarn `application -status` analogue). Falls back to history for
    finished jobs."""
    rpc = _coordinator_rpc(args.app_id, args.workdir)
    if rpc is not None:
        try:
            report = rpc.call("get_application_report")
            print(f"app_id:   {report['app_id']}")
            print(f"status:   {report['status']}")
            print(f"attempt:  {report['attempt']} "
                  f"(retries left: {report['retries_left']}, "
                  f"preemption retries left: "
                  f"{report.get('preemption_retries_left', '?')})")
            if report.get("recovered"):
                print(f"recovered: yes (coordinator generation "
                      f"{report.get('generation', '?')})")
            gang = report.get("gang_size") or {}
            if gang:
                sizes = "  ".join(f"{j}×{n}"
                                  for j, n in sorted(gang.items()))
                el = report.get("elastic") or {}
                suffix = ""
                if el:
                    suffix = f"  (mgen {el.get('mgen', '?')}"
                    if el.get("resizing"):
                        suffix += (f", RESIZING to "
                                   f"{el.get('target_size', '?')}")
                    suffix += ")"
                print(f"gang:     {sizes}{suffix}")
            if report.get("failure_reason"):
                print(f"reason:   {report['failure_reason']}")
            if report.get("failure_domain"):
                print(f"domain:   {report['failure_domain']}")
            if report.get("tb_url"):
                print(f"tb_url:   {report['tb_url']}")
            for t in report.get("tasks", []):
                print(f"  {t['name']}:{t['index']:<3} {t['status']:<10} "
                      f"{t.get('host', '') or ''}{_fmt_hb_age(t)}"
                      f"{_fmt_progress(t)}{_fmt_exit(t)}")
            return 0
        except Exception as e:  # noqa: BLE001
            print(f"(coordinator unreachable: {e}; trying history)",
                  file=sys.stderr)
    from tony_tpu.events import history

    root = _history_root(args)
    for r in history.list_jobs(root):
        if r.app_id == args.app_id:
            print(f"app_id:   {r.app_id}")
            print(f"status:   {r.status or 'RUNNING'}")
            print(f"user:     {r.user}")
            print(f"started:  {r.started_iso}")
            return 0
    print(f"unknown application {args.app_id} (not running under "
          f"{_default_workdir(args.workdir)}, no history under {root})",
          file=sys.stderr)
    return 1


def _fmt_exit(task: dict) -> str:
    """Decoded exit-signal suffix for a failed task's status row —
    '-9'/'137' render as 'SIGKILL (signal 9; likely OOM-killer ...)'
    via the shared decoder the rule engine uses too."""
    code = task.get("exit_code")
    if code in (None, 0):
        return ""
    from tony_tpu.diagnosis.exitcodes import describe_exit

    return f"  {describe_exit(code)}"


def _fmt_hb_age(task: dict) -> str:
    """Heartbeat-age column for a status row, sourced from the same
    liveness map the coordinator's heartbeat monitor expires on (absent
    for terminal/unregistered tasks)."""
    age = task.get("last_heartbeat_age_s")
    if age is None:
        return ""
    return f"  hb={float(age):.1f}s"


def _fmt_progress(task: dict) -> str:
    """One-line progress-liveness suffix for a status row: step counter,
    rate, stall age, and the hang/straggler verdicts (coordinator
    application_report 'progress' field; absent for uninstrumented or
    terminal tasks)."""
    p = task.get("progress") or {}
    if not p:
        return ""
    state = p.get("state", "")
    if "steps" not in p:
        return f"  [{state}]" if state else ""
    out = f"  steps={p['steps']:g}"
    if p.get("rate_steps_per_s") is not None:
        out += f" ({p['rate_steps_per_s']:g}/s)"
    if p.get("stalled_s", 0) and float(p["stalled_s"]) >= 1.0:
        out += f" stalled {float(p['stalled_s']):.0f}s"
    if state in ("hung", "straggler"):
        out += f" {state.upper()}"
    return out


def _cmd_profile(args: argparse.Namespace) -> int:
    """On-demand device capture from a RUNNING job: sends a PROFILE
    directive (riding the heartbeat response) to the chosen task, which
    arms jax.profiler at its next step boundary for N steps; polls until
    the artifact lands in the job dir (portal /profile/<app> lists it).
    A failed/unsupported capture reports PROFILE_FAILED and the job
    keeps training — this command can never hurt a live job."""
    rpc = _coordinator_rpc(args.app_id, args.workdir)
    if rpc is None:
        print(f"no coordinator address for {args.app_id} under "
              f"{_default_workdir(args.workdir)} (job finished? wrong "
              f"--workdir?) — on-demand profiling needs a live job",
              file=sys.stderr)
        return 1
    try:
        res = rpc.call("profile.start", steps=args.steps,
                       task=args.task or "")
        if not isinstance(res, dict) or not res.get("ok"):
            msg = res.get("message", "refused") \
                if isinstance(res, dict) else str(res)
            print(f"profile refused: {msg}", file=sys.stderr)
            return 1
        req_id = res["id"]
        print(f"profiling {res['task']} for {res['steps']} step(s) "
              f"(request {req_id}) — waiting for the capture...")
        deadline = time.monotonic() + args.timeout
        while time.monotonic() < deadline:
            st = rpc.call("profile.status")
            req = next((r for r in st.get("requests", [])
                        if r.get("id") == req_id), None)
            if req and req.get("status") == "captured":
                print(f"captured: {req['dir']}")
                print("open it in TensorBoard's profile plugin or "
                      "Perfetto; the portal lists it at "
                      f"/profile/{args.app_id}")
                return 0
            if req and req.get("status") == "failed":
                print(f"capture FAILED: {req.get('error', '?')} "
                      f"(the job keeps training)", file=sys.stderr)
                return 1
            time.sleep(args.interval)
        print(f"capture still pending after {args.timeout:.0f}s (is the "
              f"task stepping? check `tony-tpu top {args.app_id}`)",
              file=sys.stderr)
        return 1
    except Exception as e:  # noqa: BLE001
        print(f"profile failed (coordinator gone?): {e}", file=sys.stderr)
        return 1
    finally:
        rpc.close()


def _cmd_bench(args: argparse.Namespace) -> int:
    """`tony-tpu bench diff <base.json> <candidate.json>` — the bench
    regression gate (tony_tpu/profiling/benchdiff.py): nonzero exit when
    the candidate regresses any comparable metric (headline throughput,
    cold-start phases, step phases) past the tolerance."""
    from tony_tpu.profiling import benchdiff

    argv = [args.base, args.candidate, "--tolerance",
            str(args.tolerance)]
    if args.json:
        argv.append("--json")
    return benchdiff.main(argv)


_SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def _sparkline(values) -> str:
    vals = [max(0.0, float(v)) for v in values][-24:]
    if not vals:
        return ""
    hi = max(vals) or 1.0
    return "".join(_SPARK_BLOCKS[min(7, int(7 * v / hi))] for v in vals)


def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if n < 1024 or unit == "TiB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024
    return "?"


#: phase → bar glyph, in canonical draw order (tony_tpu/profiling/):
#: d=data_wait h=h2d C=step_compute m=comms k=ckpt_stall e=eval ·=other
_PHASE_GLYPHS = (("data_wait", "d"), ("h2d", "h"), ("step_compute", "C"),
                 ("comms", "m"), ("ckpt_stall", "k"), ("eval", "e"),
                 ("other", "·"))


def _phase_bar(fractions: dict, width: int = 12) -> str:
    """Proportional per-phase bar for a top row: 'dddCCCCCCCC·' means
    ~25% input wait, ~67% compute, ~8% unattributed."""
    if not fractions:
        return ""
    out = []
    for name, glyph in _PHASE_GLYPHS:
        try:
            n = int(round(float(fractions.get(name, 0.0)) * width))
        except (TypeError, ValueError):
            n = 0
        out.append(glyph * n)
    return "".join(out)[:width + 2]


#: coordinator phase → bar glyph (coordinator/coordphases.py order):
#: J=journal_fsync b=beacon_fold h=hb_scan r=rpc_serve z=rendezvous
#: p=prom_export ·=idle/other
_COORD_PHASE_GLYPHS = (("journal_fsync", "J"), ("beacon_fold", "b"),
                       ("hb_scan", "h"), ("rpc_serve", "r"),
                       ("rendezvous_barrier", "z"), ("prom_export", "p"),
                       ("idle", "·"), ("other", "·"))


def _coord_phase_bar(fractions: dict, width: int = 16) -> str:
    """Proportional control-plane phase bar for the top coord row:
    'JJJr············' means ~19% journal fsync, ~6% rpc, rest idle."""
    if not fractions:
        return ""
    out = []
    for name, glyph in _COORD_PHASE_GLYPHS:
        try:
            n = int(round(float(fractions.get(name, 0.0)) * width))
        except (TypeError, ValueError):
            n = 0
        out.append(glyph * n)
    return "".join(out)[:width + 2]


def _render_top(snap: dict) -> str:
    """One frame of the `tony-tpu top` live view from a metrics.live
    snapshot: per-task utilization + heartbeat age + a steps/s sparkline
    (the coordinator's ring-buffer series) + the per-phase step-time
    attribution bar and the live bottleneck verdict."""
    gang = snap.get("gang_size") or {}
    gang_col = "  gang=" + ",".join(
        f"{j}×{n}" for j, n in sorted(gang.items())) if gang else ""
    el = snap.get("elastic") or {}
    mgen_col = f"  mgen={el.get('mgen')}" if el else ""
    if el.get("resizing"):
        mgen_col += f" (resizing->{el.get('target_size', '?')})"
    lines = [f"{snap.get('app_id', '?')}  status={snap.get('status', '?')}"
             f"  epoch={snap.get('session_id', '?')}"
             f"  generation={snap.get('generation', '?')}"
             f"{gang_col}{mgen_col}"]
    perf = snap.get("perf") or {}
    if perf.get("verdict"):
        lines.append(f"perf: {perf['verdict']} — {perf.get('summary', '')}")
    al = snap.get("alerts") or {}
    if al.get("degraded"):
        lines.append("alerts: DEGRADED — evaluation disabled after a "
                     "fault")
    for r in al.get("firing") or []:
        lines.append(f"ALERT [{r.get('severity', '?')}] "
                     f"{r.get('rule', '?')} value={r.get('value')}"
                     + (f" — {r['summary']}" if r.get("summary")
                        else ""))
    coord = snap.get("coord") or {}
    if coord:
        # Control-plane self row: is the COORDINATOR keeping up — tick
        # duration, beat/journal throughput, fsync p99 — visible during
        # an incident, not just in post-hoc metrics.
        tick = coord.get("tick_s")
        p99 = coord.get("journal_fsync_p99_s")
        line = (f"coord: tick="
                f"{(f'{tick * 1e3:.1f}ms' if tick is not None else '-')}"
                f"  beats/s={coord.get('beats_per_s', '-')}"
                f"  journal/s={coord.get('journal_records_per_s', '-')}"
                f"  fsync p99="
                f"{(f'{p99 * 1e3:.1f}ms' if p99 is not None else '-')}"
                f"  reg={coord.get('registered_tasks', '-')}")
        bar = _coord_phase_bar(coord.get("phases") or {})
        if bar:
            line += f"  [{bar}]"
        lines.append(line)
        if coord.get("verdict") and coord["verdict"] != "COORD_HEALTHY":
            lines.append(f"coord verdict: {coord['verdict']} — "
                         f"{coord.get('summary', '')}")
    lines.append(
        f"{'TASK':<14}{'STATUS':<11}{'STEPS':>8}{'STEPS/S':>9}"
        f"{'MFU':>7}{'HBM':>10}{'RSS':>10}{'HB AGE':>8}  "
        f"{'STATE':<11}{'PHASES':<14}TREND")
    for t in snap.get("tasks", []):
        steps = t.get("steps")
        rate = t.get("steps_per_sec")
        mfu = t.get("mfu")
        hb = t.get("heartbeat_age_s")
        lines.append(
            f"{t.get('task', '?'):<14}{t.get('status', '?'):<11}"
            f"{(f'{steps:g}' if steps is not None else '-'):>8}"
            f"{(f'{rate:.2f}' if rate is not None else '-'):>9}"
            f"{(f'{mfu:.3f}' if mfu is not None else '-'):>7}"
            f"{_fmt_bytes(t.get('hbm_bytes')):>10}"
            f"{_fmt_bytes(t.get('rss_bytes')):>10}"
            f"{(f'{hb:.1f}s' if hb is not None else '-'):>8}  "
            f"{t.get('state', '') or '-':<11}"
            f"{_phase_bar(t.get('phases') or {}) or '-':<14}"
            f"{_sparkline(t.get('steps_per_sec_history', []))}")
    return "\n".join(lines)


def _cmd_top(args: argparse.Namespace) -> int:
    """Live utilization view for a RUNNING job (the `top` for a gang):
    polls the coordinator's metrics.live RPC — the same registry behind
    the portal's /metrics exposition — and redraws in place. --once
    prints a single snapshot (scripts, tests)."""
    rpc = _coordinator_rpc(args.app_id, args.workdir)
    if rpc is None:
        print(f"no coordinator address for {args.app_id} under "
              f"{_default_workdir(args.workdir)} (job finished? wrong "
              f"--workdir?) — `tony-tpu metrics` views need a live job",
              file=sys.stderr)
        return 1
    try:
        while True:
            try:
                snap = rpc.call("metrics.live")
            except Exception as e:  # noqa: BLE001
                print(f"coordinator unreachable: {e}", file=sys.stderr)
                return 1
            frame = _render_top(snap)
            if args.once:
                print(frame)
                return 0
            # Clear + home, then one frame: flicker-free enough without
            # curses, and plain pipes just see frames separated by FF.
            print("\x1b[2J\x1b[H" + frame
                  if sys.stdout.isatty() else frame, flush=True)
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def _cmd_trace(args: argparse.Namespace) -> int:
    """Export a job's span log as Chrome/Perfetto trace_events JSON
    (load at https://ui.perfetto.dev or chrome://tracing). The span log
    lives in the job's history dir next to the jhist stream; works on
    running AND finished jobs."""
    from tony_tpu import constants, tracing
    from tony_tpu.events import history

    if args.fleet:
        return _trace_fleet(args)
    if not args.app_id:
        print("trace needs an app_id (or --fleet <fleet_dir>)",
              file=sys.stderr)
        return 2
    root = _history_root(args)
    job_dir = history.list_job_dirs(root).get(args.app_id)
    if job_dir is None:
        print(f"unknown application {args.app_id} under {root}",
              file=sys.stderr)
        return 1
    path = os.path.join(job_dir, constants.TRACE_FILE)
    if not os.path.exists(path):
        print(f"no span log at {path} — the job ran with "
              f"tony.trace.enabled=false, or predates tracing",
              file=sys.stderr)
        return 1
    records = tracing.load_records(path)
    if args.cold_start:
        # Per-phase submit→first-step decomposition (the bench's phase
        # artifact, on demand for any job): consecutive boundary
        # intervals, so the phases sum exactly to the total.
        try:
            bd = tracing.cold_start_breakdown(records)
        except RuntimeError as e:
            print(f"cold-start breakdown unavailable: {e}",
                  file=sys.stderr)
            return 1
        print(f"{args.app_id}  submit -> first step: {bd['total_s']:.2f}s"
              f"  (task {bd['task'] or '?'})")
        for phase, secs in bd["phases"].items():
            bar = "#" * min(60, int(60 * secs / max(bd["total_s"], 1e-9)))
            print(f"  {phase:<10}{secs:>8.2f}s  {bar}")
        if bd["span_durations"]:
            print("  raw span durations (may overlap):")
            for name, secs in sorted(bd["span_durations"].items()):
                print(f"    {name:<28}{secs:>8.2f}s")
        return 0
    payload = tracing.to_trace_events(records)
    text = json.dumps(payload, indent=1)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(text)
        print(f"wrote {args.out}", file=sys.stderr)
    else:
        print(text)
    n_spans = sum(1 for e in payload["traceEvents"]
                  if e.get("ph") == "X")
    unclosed = payload.get("unclosedSpans", [])
    print(f"{n_spans} spans, {len(unclosed)} unclosed"
          + (f" ({', '.join(unclosed)})" if unclosed else ""),
          file=sys.stderr)
    return 0


def _trace_fleet(args: argparse.Namespace) -> int:
    """`tony-tpu trace --fleet <fleet_dir>`: merge the fleet daemon's
    own span log (queue spans, fleet.job lifetimes, preempt/restore
    instants) with EVERY job's span log under the fleet's history root
    — all sharing the fleet trace id the grants injected — into one
    Perfetto export of the whole pool."""
    from tony_tpu import constants, tracing
    from tony_tpu.fleet import ledger as fledger

    fleet_dir = os.path.abspath(os.path.expanduser(args.fleet))
    fleet_trace_path = os.path.join(fleet_dir, constants.TRACE_FILE)
    if not os.path.exists(fleet_trace_path):
        print(f"no fleet span log at {fleet_trace_path} — not a fleet "
              f"dir, or the daemon predates fleet tracing",
              file=sys.stderr)
        return 1
    records = tracing.load_records(fleet_trace_path)
    n_jobs = 0
    for app_id, job_dir in sorted(
            fledger.job_history_dirs(fleet_dir).items()):
        path = os.path.join(job_dir, constants.TRACE_FILE)
        if not os.path.exists(path):
            continue
        job_records = tracing.load_records(path)
        # Prefix the task track with the app id so 40 jobs' worker:0
        # rows stay distinguishable on the merged timeline.
        for rec in job_records:
            if rec.get("task"):
                rec["task"] = f"{app_id}/{rec['task']}"
            elif rec.get("svc") in ("client", "coordinator"):
                rec["task"] = app_id
        records.extend(job_records)
        n_jobs += 1
    payload = tracing.to_trace_events(records)
    text = json.dumps(payload, indent=1)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(text)
        print(f"wrote {args.out}", file=sys.stderr)
    else:
        print(text)
    n_spans = sum(1 for e in payload["traceEvents"]
                  if e.get("ph") == "X")
    unclosed = payload.get("unclosedSpans", [])
    print(f"fleet trace {payload.get('traceId', '?')}: {n_jobs} "
          f"job(s), {n_spans} spans, {len(unclosed)} unclosed"
          + (f" ({', '.join(unclosed[:8])})" if unclosed else ""),
          file=sys.stderr)
    return 0


def _cmd_diagnose(args: argparse.Namespace) -> int:
    """Automatic failure diagnosis: print the incident report for a job
    — verdict category, blamed task, evidence lines, the user traceback
    / stack-dump excerpt verbatim, and the causal timeline. Finished
    jobs serve the coordinator-written incident.json (recompute with
    --fresh); live jobs get a PROVISIONAL read computed on the spot.
    Works post-hoc on any history dir, including one copied off a dead
    host."""
    from tony_tpu import constants, diagnosis
    from tony_tpu.events import history

    root = _history_root(args)
    job_dir = history.list_job_dirs(root).get(args.app_id)
    if job_dir is None:
        print(f"unknown application {args.app_id} under {root}",
              file=sys.stderr)
        return 1
    live = history.find_history_file(job_dir) is None
    incident = None
    if not live and not args.fresh:
        incident = diagnosis.load_incident(
            os.path.join(job_dir, constants.INCIDENT_FILE))
    if incident is None:
        incident = diagnosis.diagnose_job_dir(job_dir, app_id=args.app_id,
                                              provisional=live)
    if args.json:
        print(json.dumps(incident, indent=1, sort_keys=True))
        return 0
    if incident.get("status") == "SUCCEEDED":
        print(f"{args.app_id} SUCCEEDED — nothing to diagnose "
              f"(full report follows for the curious)", file=sys.stderr)
    print(diagnosis.render_text(incident))
    return 0


def _render_alert_rows(res: dict) -> str:
    """Shared `alerts` table for job and fleet scope: one row per rule
    with its state-machine position, plus firing summaries."""
    lines = []
    if res.get("degraded"):
        lines.append("alerting: DEGRADED — evaluation disabled after a "
                     "fault (restart the evaluator to re-arm)")
    rows = res.get("alerts") or []
    if not rows:
        lines.append("no alert rules evaluated")
        return "\n".join(lines)
    lines.append(f"{'RULE':<22}{'STATE':<9}{'SEV':<6}{'VALUE':>10}  "
                 f"{'FOR':>7}  SERIES")
    for r in rows:
        v = r.get("value")
        since = r.get("since_s")
        lines.append(
            f"{r.get('rule', '?'):<22}{r.get('state', '?'):<9}"
            f"{r.get('severity', '?'):<6}"
            f"{(f'{v:.4g}' if v is not None else '-'):>10}  "
            f"{(f'{since:.0f}s' if since is not None else '-'):>7}  "
            f"{r.get('series', '')}")
    for r in rows:
        if r.get("state") == "firing" and r.get("summary"):
            lines.append(f"  {r['rule']}: {r['summary']}")
    return "\n".join(lines)


def _cmd_alerts(args: argparse.Namespace) -> int:
    """SLO/alert state for one job: a RUNNING job answers live from its
    coordinator's alert engine (the alerts RPC); otherwise the
    write-ahead REC_ALERT records in the session journal are replayed —
    the firing set survives the coordinator, by design."""
    rpc = _coordinator_rpc(args.app_id, args.workdir)
    if rpc is not None:
        try:
            res = rpc.call("alerts")
            if args.json:
                print(json.dumps(res, indent=1, sort_keys=True))
            else:
                print(_render_alert_rows(res))
            return 0
        except Exception as e:  # noqa: BLE001
            print(f"(coordinator unreachable: {e}; replaying the "
                  f"journal)", file=sys.stderr)
    from tony_tpu import constants
    from tony_tpu.coordinator import journal as cjournal
    from tony_tpu.events import history

    root = _history_root(args)
    job_dir = history.list_job_dirs(root).get(args.app_id)
    if job_dir is None:
        print(f"unknown application {args.app_id} under {root}",
              file=sys.stderr)
        return 1
    path = os.path.join(job_dir, constants.JOURNAL_FILE)
    if not os.path.exists(path):
        print(f"no session journal at {path} — the job ran without "
              f"tony.coordinator.journal-enabled, so no alert "
              f"transitions were recorded", file=sys.stderr)
        return 1
    st = cjournal.replay(path)
    doc = {"app_id": args.app_id, "scope": "job", "offline": True,
           "alerts": [{"rule": rule, "state": state}
                      for rule, state in sorted(st.alerts.items())]}
    if args.json:
        print(json.dumps(doc, indent=1, sort_keys=True))
        return 0
    if not st.alerts:
        print("no alert transitions journaled")
        return 0
    print("journal replay (final state per rule):")
    for rule, state in sorted(st.alerts.items()):
        print(f"  {rule:<22}{state}")
    return 0


def _history_root(args: argparse.Namespace) -> str:
    """One default for every history-reading subcommand — four diverging
    copies would silently make history/events/logs/portal look in
    different places."""
    return args.history_root or os.path.join(_default_workdir(None),
                                             "history")


def _cmd_history(args: argparse.Namespace) -> int:
    from tony_tpu.events import history

    root = _history_root(args)
    rows = history.list_jobs(root)
    if not rows:
        print(f"no job history under {root}")
        return 0
    fmt = "{:<32} {:<10} {:<12} {:<20}"
    print(fmt.format("APP_ID", "STATUS", "USER", "STARTED"))
    for r in rows:
        print(fmt.format(r.app_id, r.status or "RUNNING", r.user,
                         r.started_iso))
    return 0


def _cmd_events(args: argparse.Namespace) -> int:
    from tony_tpu.events import history

    root = _history_root(args)
    events = history.read_job_events(root, args.app_id)
    if events is None:
        print(f"no history for {args.app_id} under {root}", file=sys.stderr)
        return 1
    for ev in events:
        print(ev)
    return 0


def _cmd_logs(args: argparse.Namespace) -> int:
    """Dump per-task stdout/stderr recorded in the job's TASK_FINISHED
    events — the terminal analogue of `yarn logs -applicationId` (the
    reference surfaced NodeManager log URLs per container,
    ``models/JobLog.java:69-80``; here the paths live in the event
    stream and the files on the submitting host's workdir)."""
    from tony_tpu.events import history

    root = _history_root(args)
    events = history.read_job_events(root, args.app_id)
    if events is None:
        print(f"no history for {args.app_id} under {root}", file=sys.stderr)
        return 1
    shown = 0
    for ev in events:
        if ev.type != "TASK_FINISHED":
            continue
        task = ev.payload.get("task", "?")
        if args.task and task != args.task:
            continue
        for path in ev.payload.get("logs", []):
            print(f"===== {task} — {path} =====")
            try:
                with open(path, "r", encoding="utf-8",
                          errors="replace") as f:
                    sys.stdout.write(f.read())
            except OSError as e:
                # stderr, and NOT counted: purged/deleted logs must not
                # let the command exit 0 having printed no content.
                print(f"{task}: {path} unreadable: {e}", file=sys.stderr)
                continue
            shown += 1
    if not shown:
        print("no readable task logs" +
              (f" for task {args.task}" if args.task else ""),
              file=sys.stderr)
        return 1
    return 0


def _cmd_portal(args: argparse.Namespace) -> int:
    """Serve the history portal (shortcut for python -m tony_tpu.portal).
    The CLI defaults to binding localhost: serving job history + raw task
    logs to every interface is an explicit choice (--host 0.0.0.0), and
    without a token it should stay local."""
    from tony_tpu.portal.server import main as portal_main

    argv = ["--history-root", _history_root(args), "--host", args.host]
    if args.port is not None:
        argv += ["--port", str(args.port)]
    if args.token:
        argv += ["--token", args.token]
    return portal_main(argv)


def _cmd_gcloud_gc(args: argparse.Namespace) -> int:
    """Janitor for leaked tony-managed TPU nodes. The provisioner deletes
    its node on release and on failed acquires, but a HARD-crashed
    coordinator (SIGKILL, power loss) can strand a billing node — the
    reference relied on YARN's ResourceManager to reap containers; with
    no RM, this command is the operator's reaper. Lists nodes carrying
    the ``tony-managed`` label (and matching --prefix); --delete deletes
    them. NEVER touches unlabeled nodes."""
    from tony_tpu.cluster.gcloud import TpuApiClient

    api = TpuApiClient(project=args.project, zone=args.zone,
                       endpoint=args.api_endpoint or None)

    def _rid(res: dict) -> str:
        return res.get("name", "").rsplit("/", 1)[-1]

    def _qr_is_managed(qr: dict) -> bool:
        for spec in (qr.get("tpu") or {}).get("nodeSpec") or []:
            labels = (spec.get("node") or {}).get("labels") or {}
            if labels.get("tony-managed") == "true":
                return True
        return False

    # Queued resources FIRST: a coordinator that died while its request
    # was WAITING leaked something with no node yet — and a granted QR's
    # node can only be deleted through its QR (the API rejects
    # nodes.delete on queued-resource-created nodes).
    all_qrs = api.list_queued_resources()
    managed_qrs = [q for q in all_qrs
                   if _qr_is_managed(q) and _rid(q).startswith(args.prefix)]
    qr_ids = {_rid(q) for q in managed_qrs}
    live_qr_ids = {_rid(q) for q in all_qrs}
    qr_node_names = {
        spec.get("nodeId", "")
        for q in managed_qrs
        for spec in (q.get("tpu") or {}).get("nodeSpec") or []}
    candidates = [
        n for n in api.list_nodes()
        if (n.get("labels", {}).get("tony-managed") == "true"
            and _rid(n).startswith(args.prefix)
            # nodes a managed QR will reap (or that name their QR) are
            # handled on the QR side
            and _rid(n) not in qr_node_names)]
    managed_nodes = [n for n in candidates if not n.get("queuedResource")]
    # Leak shape the two lists above miss: a QR-created node whose QR no
    # longer exists (externally deleted QR, partial force-delete). It has
    # a queuedResource reference, so the node path skipped it; its QR is
    # not in the live set, so the QR path never reaps it. These can only
    # be deleted via their (stale) QR name — and when that 404s, via a
    # last-resort nodes.delete.
    stale_qr_nodes = [
        (n, n["queuedResource"].rsplit("/", 1)[-1]) for n in candidates
        if n.get("queuedResource")
        and n["queuedResource"].rsplit("/", 1)[-1] not in live_qr_ids]
    if not managed_qrs and not managed_nodes and not stale_qr_nodes:
        print("no tony-managed nodes or queued resources found")
        return 0
    for q in managed_qrs:
        print(f"{_rid(q)}\tqueued-resource "
              f"{(q.get('state') or {}).get('state', '?')}")
    for n in managed_nodes:
        print(f"{_rid(n)}\tnode {n.get('state', '?')}\t"
              f"{n.get('acceleratorType', '?')}")
    for n, stale_qr in stale_qr_nodes:
        print(f"{_rid(n)}\tnode {n.get('state', '?')}\t"
              f"{n.get('acceleratorType', '?')}\t"
              f"(stale queued-resource {stale_qr})")
    if not args.delete:
        print(f"{len(managed_qrs)} queued resource(s) + "
              f"{len(managed_nodes) + len(stale_qr_nodes)} node(s); "
              f"re-run with --delete to "
              f"remove them (make sure no tony-tpu job is running "
              f"against them!)")
        return 0
    # The filter cannot tell a LEAKED resource from one a live
    # coordinator holds — repeat the warning where it matters, on the
    # destructive path.
    print("deleting — make sure no tony-tpu job is running against "
          "these resources!", file=sys.stderr)
    # Deletes are independent long-running ops: issue them ALL first,
    # then poll — N stranded resources cost one op latency, not N.
    failures = 0
    pending = []
    for qr_id in sorted(qr_ids):
        try:
            pending.append((qr_id,
                            api.delete_queued_resource(qr_id, force=True)))
        except FileNotFoundError:
            print(f"{qr_id} already gone")
        except Exception as e:  # noqa: BLE001
            failures += 1
            print(f"failed to delete {qr_id}: {e}", file=sys.stderr)
    for n in managed_nodes:
        node_id = _rid(n)
        try:
            pending.append((node_id, api.delete_node(node_id)))
        except FileNotFoundError:
            print(f"{node_id} already gone")
        except Exception as e:  # noqa: BLE001
            failures += 1
            print(f"failed to delete {node_id}: {e}", file=sys.stderr)
    for n, stale_qr in stale_qr_nodes:
        node_id = _rid(n)
        try:
            # QR-created nodes must be deleted through their QR; the stale
            # name may still resolve server-side (partial force-delete).
            pending.append((node_id,
                            api.delete_queued_resource(stale_qr,
                                                       force=True)))
        except FileNotFoundError:
            # The QR really is gone — last resort, try the node directly
            # (some API surfaces allow it once the QR record vanished).
            try:
                pending.append((node_id, api.delete_node(node_id)))
            except FileNotFoundError:
                print(f"{node_id} already gone")
            except Exception as e:  # noqa: BLE001
                failures += 1
                print(f"failed to delete {node_id} (stale qr {stale_qr}):"
                      f" {e}", file=sys.stderr)
        except Exception as e:  # noqa: BLE001
            failures += 1
            print(f"failed to delete {node_id} via stale qr {stale_qr}: "
                  f"{e}", file=sys.stderr)
    for rid, op in pending:
        try:
            api.wait_operation(op, timeout_s=300,
                               interval_s=args.poll_interval)
            print(f"deleted {rid}")
        except Exception as e:  # noqa: BLE001
            failures += 1
            print(f"failed to delete {rid}: {e}", file=sys.stderr)
    return 1 if failures else 0


def _pool_dir(args: argparse.Namespace) -> str:
    return os.path.abspath(os.path.expanduser(
        args.dir or os.path.join(_default_workdir(args.workdir), "pool")))


def _cmd_lint(args: argparse.Namespace) -> int:
    """`tony-tpu lint` — the static invariant checker (tonylint)."""
    from tony_tpu.devtools import tonylint

    argv: List[str] = []
    if args.list_rules:
        argv.append("--list")
    if args.json:
        argv.append("--json")
    if args.root:
        argv += ["--root", args.root]
    for rule in args.rule or []:
        argv += ["--rule", rule]
    return tonylint.main(argv)


def _cmd_check(args: argparse.Namespace) -> int:
    """`tony-tpu check` — the cross-artifact trace invariant checker
    (tonycheck's runtime half; devtools/invariants.py)."""
    import json as _json

    from tony_tpu.devtools import invariants
    from tony_tpu.events import history

    target = args.target
    if os.path.isdir(target):
        job_dir = target
    else:
        root = _history_root(args)
        job_dir = history.list_job_dirs(root).get(target)
        if job_dir is None:
            print(f"unknown application {target} under {root}",
                  file=sys.stderr)
            return 2
    report = invariants.check_job_dir(job_dir)
    if args.json:
        print(_json.dumps(report.to_dict(), indent=1, sort_keys=True))
    else:
        print(invariants.render_text([report]))
    return 0 if report.ok else 1


def _chaos_workdir(base: str, schedule) -> str:
    return os.path.join(base, "runs", schedule.name)


def _chaos_run_one(schedule, outdir: str, runs_root: str):
    """Execute one schedule, save its artifact, return the outcome."""
    import shutil

    from tony_tpu.chaos import artifact as chaos_artifact
    from tony_tpu.chaos import runner as chaos_runner

    workdir = _chaos_workdir(runs_root, schedule)
    if os.path.isdir(workdir):
        shutil.rmtree(workdir)
    outcome = chaos_runner.run_schedule(schedule, workdir)
    chaos_artifact.save_artifact(outdir, schedule, outcome)
    # a clean run's scratch tree is noise; a failing run's is evidence
    if outcome.ok:
        shutil.rmtree(workdir, ignore_errors=True)
    return outcome


def _cmd_chaos_run(args: argparse.Namespace) -> int:
    """`tony-tpu chaos run` — seeded multi-fault sweep."""
    from tony_tpu.chaos import schedule as chaos_schedule

    seed = int(args.seed)
    outdir = os.path.abspath(args.out)
    runs_root = os.path.join(outdir, "scratch")
    os.environ[_faults.FAULT_SEED_ENV] = str(seed)
    suites = [args.suite] if args.suite else list(chaos_schedule.SUITES)
    failed = 0
    total = 0
    t0 = time.monotonic()
    for index in range(int(args.schedules)):
        suite = suites[index % len(suites)]
        sched = chaos_schedule.plan(seed, index, suite)
        total += 1
        outcome = _chaos_run_one(sched, outdir, runs_root)
        tag = "ok" if outcome.ok else "FAIL"
        sites = ", ".join(i.site for i in sched.injections)
        print(f"{sched.name} [{suite:8s}] {outcome.status:9s} "
              f"{outcome.failure_domain or '-':16s} {tag}  "
              f"({sites or 'no injections'})")
        if not outcome.ok:
            failed += 1
            for v in outcome.violations:
                print(f"    {v.rung}: {v.detail}")
            if args.fail_fast:
                break
    dt = time.monotonic() - t0
    print(f"chaos: {total} schedule(s), {failed} failing, "
          f"{dt:.1f}s (seed {seed})")
    if failed:
        print(f"artifacts + scratch trees under {outdir}; shrink with "
              f"`tony-tpu chaos shrink <artifact>`")
    return 1 if failed else 0


def _cmd_chaos_replay(args: argparse.Namespace) -> int:
    """`tony-tpu chaos replay` — re-run an artifact's schedule and
    prove the planner regenerates it bit-identically."""
    from tony_tpu.chaos import artifact as chaos_artifact
    from tony_tpu.chaos import schedule as chaos_schedule

    doc = chaos_artifact.load_artifact(args.artifact)
    sched = chaos_artifact.schedule_from_doc(doc)
    os.environ[_faults.FAULT_SEED_ENV] = str(sched.seed)
    if not doc.get("shrunk_from"):
        # full schedules must replan bit-identically — THE determinism
        # contract; shrunk ones are subsets the planner never emits
        replanned = chaos_schedule.plan(sched.seed, sched.index,
                                        sched.suite)
        if replanned.as_dict() != sched.as_dict():
            print("REPLAY MISMATCH: the planner no longer regenerates "
                  "this artifact's schedule — planner drift:",
                  file=sys.stderr)
            print(f"  recorded:  {sched.as_dict()}", file=sys.stderr)
            print(f"  replanned: {replanned.as_dict()}", file=sys.stderr)
            return 2
    outdir = os.path.abspath(args.out)
    outcome = _chaos_run_one(sched, outdir, os.path.join(outdir,
                                                         "scratch"))
    recorded = chaos_artifact.outcome_from_doc(doc)
    print(f"{sched.name}: recorded {recorded.status}"
          f"{'/' + recorded.failure_domain if recorded.failure_domain else ''}"
          f" ({'ok' if recorded.ok else 'FAIL'}), replay "
          f"{outcome.status}"
          f"{'/' + outcome.failure_domain if outcome.failure_domain else ''}"
          f" ({'ok' if outcome.ok else 'FAIL'})")
    for v in outcome.violations:
        print(f"    {v.rung}: {v.detail}")
    return 0 if outcome.ok == recorded.ok else 1


def _cmd_chaos_shrink(args: argparse.Namespace) -> int:
    """`tony-tpu chaos shrink` — ddmin a failing artifact to the
    minimal injection set that still violates the ladder."""
    import dataclasses

    from tony_tpu.chaos import artifact as chaos_artifact
    from tony_tpu.chaos import shrink as chaos_shrink

    doc = chaos_artifact.load_artifact(args.artifact)
    sched = chaos_artifact.schedule_from_doc(doc)
    os.environ[_faults.FAULT_SEED_ENV] = str(sched.seed)
    outdir = os.path.abspath(args.out)
    runs_root = os.path.join(outdir, "scratch")
    attempts = [0]

    def _fails(injections) -> bool:
        attempts[0] += 1
        candidate = dataclasses.replace(sched, injections=list(injections))
        outcome = _chaos_run_one(candidate, outdir, runs_root)
        print(f"  shrink run #{attempts[0]}: "
              f"{len(injections)} injection(s) -> "
              f"{'FAIL' if not outcome.ok else 'ok'}")
        return not outcome.ok

    try:
        minimal = chaos_shrink.ddmin(sched.injections, _fails,
                                     max_runs=int(args.max_runs))
    except ValueError as e:
        print(f"error: {e} — is {args.artifact} a FAILING artifact?",
              file=sys.stderr)
        return 2
    shrunk = dataclasses.replace(sched, injections=minimal)
    final = _chaos_run_one(shrunk, outdir, runs_root)
    path = chaos_artifact.save_artifact(
        outdir, shrunk, final,
        shrunk_from={"injections": len(sched.injections),
                     "artifact": os.path.abspath(args.artifact)},
        note=args.note or "")
    print(f"shrunk {len(sched.injections)} -> {len(minimal)} "
          f"injection(s) in {attempts[0]} run(s):")
    for inj in minimal:
        print(f"  {inj.site} = {inj.spec}")
    print(f"minimal repro saved to {path}")
    return 0


def _cmd_pool(args: argparse.Namespace) -> int:
    """Warm-executor-pool operations (tony_tpu/pool.py): `start` spawns
    the daemon detached and waits for its endpoint; `status` prints the
    fleet; `stop` asks the daemon to shut idle workers down (leased
    executors belong to their jobs and are left alone). Point submits at
    it with tony.pool.dir=<dir> — see the Cold start runbook in
    docs/operations.md."""
    import subprocess

    from tony_tpu import constants
    from tony_tpu.pool import PoolClient
    from tony_tpu.utils import proc as procutil

    pool_dir = _pool_dir(args)
    addr_path = os.path.join(pool_dir, constants.POOL_ADDR_FILE)
    if args.action == "start":
        if os.path.exists(addr_path):
            client = PoolClient(pool_dir)
            try:
                st = client.call("pool.status")
                print(f"pool already running under {pool_dir} "
                      f"({st.get('ready', '?')} ready / "
                      f"{st.get('size', '?')} size)")
                return 0
            except Exception:  # noqa: BLE001 — stale addr from a dead pool
                os.unlink(addr_path)
            finally:
                client.close()
        os.makedirs(pool_dir, exist_ok=True)
        from tony_tpu.conf.config import TonyTpuConfig

        conf = TonyTpuConfig.from_layers(config_file=args.conf_file,
                                         overrides=tuple(args.conf or []))
        size = args.size if args.size is not None \
            else conf.get_int(K.POOL_SIZE, 2)
        preload = args.preload if args.preload is not None \
            else str(conf.get(K.POOL_PRELOAD, "jax"))
        max_age = conf.get_int(K.POOL_MAX_LEASE_AGE_S, 600)
        jax_cache = str(conf.get(K.JAX_COMPILE_CACHE_DIR, "") or "")
        pool_log = open(os.path.join(pool_dir, "pool.log"), "ab")
        cmd = [sys.executable, "-m", "tony_tpu.pool", "serve",
               "--dir", pool_dir, "--size", str(size),
               "--preload", preload, "--max-lease-age-s", str(max_age)]
        if jax_cache:
            cmd += ["--jax-cache-dir", jax_cache]
        proc = subprocess.Popen(cmd, stdout=pool_log,
                                stderr=subprocess.STDOUT,
                                start_new_session=True)
        pool_log.close()

        def read_addr():
            if proc.poll() is not None:
                raise RuntimeError(
                    f"pool daemon exited with {proc.returncode}; see "
                    f"{os.path.join(pool_dir, 'pool.log')}")
            return os.path.exists(addr_path) or None

        if procutil.poll_till_non_null(read_addr, interval_s=0.1,
                                       timeout_s=60) is None:
            print(f"pool daemon never published its endpoint under "
                  f"{pool_dir}", file=sys.stderr)
            return 1
        print(f"pool running under {pool_dir} (size {size}, "
              f"preload {preload!r}); submit with "
              f"--conf {K.POOL_DIR}={pool_dir}")
        return 0
    client = PoolClient(pool_dir)
    try:
        if args.action == "status":
            st = client.call("pool.status")
            print(f"{pool_dir}  size={st['size']}  ready={st['ready']}  "
                  f"leased={st['leased']}")
            for w in st.get("workers", []):
                print(f"  {w['worker']}  pid={w['pid']:<8}"
                      f"{w['state']:<9}age={w['age_s']:.0f}s"
                      + (f"  task={w['task']}" if w.get("task") else ""))
            return 0
        if args.action == "stop":
            client.call("pool.stop")
            print(f"pool under {pool_dir} stopping (leased executors "
                  f"are left to their jobs)")
            return 0
    except Exception as e:  # noqa: BLE001
        print(f"no reachable pool under {pool_dir}: {e}", file=sys.stderr)
        return 1
    finally:
        client.close()
    return 1


def _fleet_dir(args: argparse.Namespace) -> str:
    return os.path.abspath(os.path.expanduser(
        args.dir or str(args.conf_obj.get(K.FLEET_DIR, "") or "")
        or os.path.join(_default_workdir(getattr(args, "workdir", None)),
                        "fleet")))


def _fleet_conf(args: argparse.Namespace):
    from tony_tpu.conf.config import TonyTpuConfig

    return TonyTpuConfig.from_layers(
        config_file=getattr(args, "conf_file", None),
        overrides=tuple(getattr(args, "conf", None) or []))


def _render_fleet_top(snap: dict) -> str:
    """One frame of `tony-tpu fleet top`: pool occupancy, per-tenant
    usage vs quota WITH ledger goodput%, the fleet goodput headline,
    queue depth + wait quantiles, and one row per job — queued jobs
    show their live wait and a `held:` column (the explainer's
    top-line answer; `fleet explain <job>` has the full timeline)."""
    pool = snap.get("pool") or {}
    qw = snap.get("queue_wait") or {}
    lines = [
        f"{snap.get('fleet_dir', '?')}  generation="
        f"{snap.get('generation', '?')}  hosts: {pool.get('used', '?')}/"
        f"{pool.get('total', '?')} used ({pool.get('free', '?')} free, "
        f"{pool.get('slices', '?')}x{pool.get('hosts_per_slice', '?')})"
        f"  queue={snap.get('queue_depth', '?')}"
        f"  wait p50={qw.get('p50_s', 0)}s p99={qw.get('p99_s', 0)}s"]
    ledger = snap.get("ledger") or {}
    fleet_led = ledger.get("fleet") or {}
    if fleet_led.get("goodput_fraction") is not None:
        warm = fleet_led.get("warm_start_fraction")
        lines.append(
            f"goodput: {float(fleet_led['goodput_fraction']):.1%} of "
            f"{fleet_led.get('held_chip_s', 0)} chip-seconds held"
            + (f"  warm starts: {float(warm):.0%}"
               if warm is not None else "")
            + (f"  preempt-lost: "
               f"{fleet_led.get('lost_preempted_chip_s', 0)} chip-s"
               if fleet_led.get("lost_preempted_chip_s") else ""))
    health = snap.get("health") or {}
    if health.get("cordoned") or health.get("sick_slices"):
        lines.append(
            "health: cordoned "
            + (", ".join(health["cordoned"]) or "-")
            + (f"  sick slices: {health['sick_slices']}"
               if health.get("sick_slices") else ""))
    fal = snap.get("alerts") or {}
    if fal.get("degraded"):
        lines.append("alerts: DEGRADED — evaluation disabled after a "
                     "fault")
    for r in fal.get("firing") or []:
        lines.append(f"ALERT [{r.get('severity', '?')}] "
                     f"{r.get('rule', '?')} value={r.get('value')}"
                     + (f" — {r['summary']}" if r.get("summary")
                        else ""))
    tenants = snap.get("tenants") or {}
    if tenants:
        def _tenant_cell(t, row):
            cell = f"{t}={row.get('used', 0)}/{row.get('quota') or '∞'}"
            if row.get("goodput") is not None:
                cell += f" gp={float(row['goodput']):.0%}"
            return cell
        lines.append("tenants: " + "  ".join(
            _tenant_cell(t, row) for t, row in sorted(tenants.items())))
    lines.append(f"{'JOB':<10}{'TENANT':<10}{'PRI':>4} {'STATE':<11}"
                 f"{'HOSTS':>7}  {'WAIT':>7}  {'APP / HELD'}")
    for row in snap.get("jobs", []):
        wait = row.get("wait_s")
        note = row.get("app_id") or ""
        if row.get("state") == "QUEUED":
            note = row.get("held") or row.get("denial") or note
        hosts = f"{row.get('hosts', 0)}/{row.get('hosts_requested', '?')}"
        lines.append(
            f"{row.get('job', '?'):<10}{row.get('tenant', '?'):<10}"
            f"{row.get('priority', 0):>4} {row.get('state', '?'):<11}"
            f"{hosts:>7}  "
            f"{(f'{wait:.1f}s' if wait is not None else '-'):>7}  "
            f"{note}")
    return "\n".join(lines)


def _cmd_fleet(args: argparse.Namespace) -> int:
    """Fleet operations (tony_tpu/fleet/): the persistent multi-job
    gang scheduler. `start` spawns the daemon detached (use --recover
    after a daemon crash to resume the journaled queue), `submit`
    queues a job through it, `top` watches the scheduler live — see
    the Multi-tenancy runbook in docs/operations.md."""
    import subprocess

    from tony_tpu import constants
    from tony_tpu.fleet.client import FleetClient, FleetClientError
    from tony_tpu.utils import proc as procutil

    args.conf_obj = _fleet_conf(args)
    fleet_dir = _fleet_dir(args)
    addr_path = os.path.join(fleet_dir, constants.FLEET_ADDR_FILE)
    if args.fleet_cmd == "start":
        conf = args.conf_obj
        if os.path.exists(addr_path):
            client = FleetClient(fleet_dir)
            try:
                st = client.status()
                print(f"fleet already running under {fleet_dir} "
                      f"(generation {st.get('generation', '?')}, "
                      f"{st.get('queue_depth', '?')} queued)")
                return 0
            except FleetClientError:
                os.unlink(addr_path)   # stale addr from a dead daemon
            finally:
                client.close()
        os.makedirs(fleet_dir, exist_ok=True)
        slices = args.slices if args.slices is not None \
            else conf.get_int(K.FLEET_SLICES, 1)
        hps = args.hosts_per_slice if args.hosts_per_slice is not None \
            else conf.get_int(K.FLEET_HOSTS_PER_SLICE, 8)
        quotas = args.quotas if args.quotas is not None \
            else str(conf.get(K.FLEET_QUOTAS, "") or "")
        pool_dir = args.pool_dir if args.pool_dir is not None \
            else str(conf.get(K.FLEET_POOL_DIR, "") or "")
        cache_root = args.cache_root if args.cache_root is not None \
            else str(conf.get(K.FLEET_COMPILE_CACHE_ROOT, "") or "")
        tick_s = float(conf.get(K.FLEET_TICK_INTERVAL_S, 0.5) or 0.5)
        ring = conf.get_int(K.FLEET_DECISION_RING, 64)
        ledger_s = float(conf.get(K.FLEET_LEDGER_INTERVAL_S, 5.0)
                         or 5.0)
        cmd = [sys.executable, "-m", "tony_tpu.fleet", "serve",
               "--dir", fleet_dir, "--slices", str(slices),
               "--hosts-per-slice", str(hps), "--tick-s", str(tick_s),
               "--decision-ring", str(ring),
               "--ledger-interval-s", str(ledger_s),
               "--health-enabled",
               str(int(conf.get_bool(K.HEALTH_ENABLED, True))),
               "--health-half-life-s",
               str(float(conf.get(K.HEALTH_HALF_LIFE_S, 300.0) or 300.0)),
               "--health-suspect-threshold",
               str(float(conf.get(K.HEALTH_SUSPECT_THRESHOLD, 1.0)
                         or 1.0)),
               "--health-quarantine-threshold",
               str(float(conf.get(K.HEALTH_QUARANTINE_THRESHOLD, 3.0)
                         or 3.0)),
               "--health-quarantine-s",
               str(float(conf.get(K.HEALTH_QUARANTINE_S, 120.0)
                         or 120.0)),
               "--health-probation-priority",
               str(conf.get_int(K.HEALTH_PROBATION_PRIORITY, 0)),
               "--health-blast-n",
               str(conf.get_int(K.HEALTH_BLAST_N, 2)),
               "--health-blast-window-s",
               str(float(conf.get(K.HEALTH_BLAST_WINDOW_S, 120.0)
                         or 120.0))]
        if quotas:
            cmd += ["--quotas", quotas]
        if pool_dir:
            cmd += ["--pool-dir", pool_dir]
        if cache_root:
            cmd += ["--cache-root", cache_root]
        if args.recover:
            cmd.append("--recover")
        flog = open(os.path.join(fleet_dir, "fleet.log"), "ab")
        proc = subprocess.Popen(cmd, stdout=flog,
                                stderr=subprocess.STDOUT,
                                start_new_session=True)
        flog.close()

        def read_addr():
            if proc.poll() is not None:
                raise RuntimeError(
                    f"fleet daemon exited with {proc.returncode}; see "
                    f"{os.path.join(fleet_dir, 'fleet.log')}")
            return os.path.exists(addr_path) or None

        if procutil.poll_till_non_null(read_addr, interval_s=0.1,
                                       timeout_s=60) is None:
            print(f"fleet daemon never published its endpoint under "
                  f"{fleet_dir}", file=sys.stderr)
            return 1
        print(f"fleet running under {fleet_dir} ({slices} slice(s) x "
              f"{hps} hosts"
              + (f", quotas {quotas}" if quotas else "")
              + (", recovered" if args.recover else "") + ")")
        print(f"submit with `tony-tpu fleet submit --dir {fleet_dir} "
              f"--tenant <t> --hosts <n> --conf ...`")
        return 0
    if args.fleet_cmd == "diagnose":
        # Offline by design: the verdict must survive the daemon (a
        # dead scheduler is exactly when you want to diagnose the
        # fleet). The daemon's own periodic fleet.incident.json is the
        # live twin; this recomputes fresh from the fleet dir.
        from tony_tpu.fleet import diagnose as fdiagnose
        from tony_tpu.fleet.journal import FleetJournalError

        try:
            doc = fdiagnose.build_incident(
                fdiagnose.bundle_from_dir(fleet_dir))
        except FleetJournalError as e:
            print(f"{e}", file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps(doc, indent=1, sort_keys=True))
        else:
            print(fdiagnose.render_text(doc))
        return 0
    if args.fleet_cmd == "alerts":
        # Dual-path like explain: a live daemon answers from its
        # engine; otherwise the REC_FLEET_ALERT records are replayed.
        from tony_tpu.fleet import journal as fjournal
        from tony_tpu.fleet.journal import FleetJournalError

        client = FleetClient(fleet_dir)
        try:
            res = client.alerts()
        except FleetClientError:
            try:
                st = fjournal.replay(os.path.join(
                    fleet_dir, constants.FLEET_JOURNAL_FILE))
            except FleetJournalError as e:
                print(f"{e}", file=sys.stderr)
                return 1
            res = {"fleet_dir": fleet_dir, "scope": "fleet",
                   "offline": True,
                   "alerts": [{"rule": rule, "state": state}
                              for rule, state
                              in sorted(st.alerts.items())]}
        finally:
            client.close()
        if args.json:
            print(json.dumps(res, indent=1, sort_keys=True))
        elif res.get("offline"):
            if not res["alerts"]:
                print("no fleet alert transitions journaled")
            else:
                print("journal replay (final state per rule):")
                for row in res["alerts"]:
                    print(f"  {row['rule']:<22}{row['state']}")
        else:
            print(_render_alert_rows(res))
        return 0
    if args.fleet_cmd == "whatif":
        # Offline by design, like diagnose: the time machine replays a
        # RECORDED journal — it never needs (or touches) a live daemon.
        from tony_tpu.fleet import simulator as fsim
        from tony_tpu.fleet.journal import FleetJournalError

        try:
            report = fsim.whatif_from_dir(
                fleet_dir, sets=args.set, quotas=args.quota,
                pool=args.pool or None, priorities=args.priority,
                sweeps=args.sweep)
        except FleetJournalError as e:
            print(f"{e}", file=sys.stderr)
            return 1
        except ValueError as e:
            print(f"whatif: {e}", file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps(report, indent=1, sort_keys=True))
        else:
            print(fsim.render_report(report))
        par = report.get("parity") or {}
        if args.expect_parity and not par.get("ok"):
            return 1
        return 0
    if args.fleet_cmd == "explain":
        from tony_tpu.fleet import diagnose as fdiagnose
        from tony_tpu.fleet.journal import FleetJournalError

        client = FleetClient(fleet_dir)
        try:
            res = client.explain(args.job)
        except FleetClientError:
            # No live daemon: replay the journal's decision records —
            # the ring is bounded, the journal is the full history.
            try:
                res = fdiagnose.offline_explain(fleet_dir, args.job)
            except FleetJournalError as e:
                print(f"{e}", file=sys.stderr)
                return 1
        finally:
            client.close()
        if not res.get("ok"):
            print(f"explain refused: {res.get('message', '?')}",
                  file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps(res, indent=1, sort_keys=True))
        else:
            print(fdiagnose.render_explain(res))
        return 0
    client = FleetClient(fleet_dir)
    try:
        if args.fleet_cmd == "stop":
            client.stop()
            print(f"fleet under {fleet_dir} stopping (running jobs are "
                  f"left to their tenants)")
            return 0
        if args.fleet_cmd == "status":
            print(_render_fleet_top(client.status()))
            return 0
        if args.fleet_cmd == "top":
            while True:
                frame = _render_fleet_top(client.status())
                if args.once:
                    print(frame)
                    return 0
                print("\x1b[2J\x1b[H" + frame
                      if sys.stdout.isatty() else frame, flush=True)
                time.sleep(args.interval)
        if args.fleet_cmd == "cancel":
            res = client.cancel(args.job)
            if not res.get("ok"):
                print(f"cancel refused: {res.get('message', '?')}",
                      file=sys.stderr)
                return 1
            print(f"{args.job}: {res.get('state', '?')}")
            return 0
        if args.fleet_cmd == "migrate":
            res = client.migrate(args.job, args.target)
            if not res.get("ok"):
                print(f"migrate refused: {res.get('message', '?')}",
                      file=sys.stderr)
                return 1
            print(f"{args.job}: migrating slice {res.get('source')} -> "
                  f"{res.get('target')} (placement {res.get('placement')})")
            print(f"watch it land with `tony-tpu fleet status` or the "
                  f"job's own `tony-tpu events` stream (GANG_MIGRATED)")
            return 0
        if args.fleet_cmd == "cordon":
            res = client.cordon(args.host, reason=args.reason)
            if not res.get("ok"):
                print(f"cordon refused: {res.get('message', '?')}",
                      file=sys.stderr)
                return 1
            print(f"{args.host}: {res.get('state', '?')}"
                  + ("" if res.get("was_free")
                     else " (leased — placements stop now, the slot "
                          "leaves the pool when its job releases)"))
            return 0
        if args.fleet_cmd == "uncordon":
            res = client.uncordon(args.host)
            if not res.get("ok"):
                print(f"uncordon refused: {res.get('message', '?')}",
                      file=sys.stderr)
                return 1
            print(f"{args.host}: {res.get('state', '?')}")
            return 0
        if args.fleet_cmd == "health":
            res = client.health()
            if not res.get("ok"):
                print(f"health refused: {res.get('message', '?')}",
                      file=sys.stderr)
                return 1
            if args.json:
                print(json.dumps(res, indent=1, sort_keys=True))
                return 0
            if not res.get("enabled"):
                print("host health: DISABLED (tony.health.enabled)")
                return 0
            print("cordoned: "
                  + (", ".join(res.get("cordoned") or []) or "-"))
            if res.get("sick_slices"):
                print(f"sick slices: {res['sick_slices']}")
            for row in res.get("hosts", []):
                ev = "; ".join(
                    str(e.get("kind", "?"))
                    + (f" in {e['job']}" if e.get("job") else "")
                    for e in row.get("evidence", []))
                print(f"  {row.get('host'):<8} {row.get('state'):<12} "
                      f"score {row.get('score', 0):<6} {ev}")
            return 0
        if args.fleet_cmd == "submit":
            # Ship only the EXPLICIT conf entries: registry defaults
            # would shadow the fleet's own grant-time injections
            # (pool dir, compile cache, elastic knobs are setdefault'd
            # on the daemon side).
            reg = K.registry()
            explicit = {
                k: v for k, v in args.conf_obj.as_dict().items()
                if k not in reg or v != reg[k].default}
            res = client.submit(
                args.tenant, args.hosts, priority=args.priority,
                min_hosts=args.min_hosts, model=args.model,
                conf=explicit)
            if not res.get("ok"):
                print(f"submit refused: {res.get('message', '?')}",
                      file=sys.stderr)
                return 1
            job = res["job"]
            print(f"queued {job} (tenant {args.tenant}, "
                  f"{args.hosts} host(s), priority {args.priority})")
            if not args.follow:
                return 0
            while True:
                row = next((r for r in client.status().get("jobs", [])
                            if r.get("job") == job), None)
                if row and row.get("state") in ("FINISHED", "FAILED",
                                                "CANCELLED"):
                    print(f"{job}: {row['state']}"
                          + (f" (app {row.get('app_id')})"
                             if row.get("app_id") else ""))
                    return 0 if row["state"] == "FINISHED" else 1
                time.sleep(1.0)
    except FleetClientError as e:
        print(f"{e}", file=sys.stderr)
        return 1
    except KeyboardInterrupt:
        return 0
    finally:
        client.close()
    return 1


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tony-tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("submit", help="submit a job and monitor it")
    s.add_argument("--conf-file", help="job config (json/yaml)")
    s.add_argument("--conf", action="append", metavar="K=V",
                   help="config override (repeatable)")
    s.add_argument("--executable", help="training script (python_binary is "
                   "prepended; reference -executes)")
    s.add_argument("--task-params", help="args appended to the default "
                   "command (reference -task_params)")
    s.add_argument("--src-dir", help="directory staged to every task "
                   "(reference -src_dir)")
    s.add_argument("--instances", type=int,
                   help="shortcut for tony.worker.instances")
    s.add_argument("--workdir", help="client workdir (default ~/.tony-tpu)")
    s.set_defaults(fn=_cmd_submit)

    n = sub.add_parser(
        "notebook",
        help="run a notebook server as a single-node job and tunnel a "
             "local port to it (reference NotebookSubmitter)")
    n.add_argument("--conf-file", help="job config (json/yaml)")
    n.add_argument("--conf", action="append", metavar="K=V",
                   help="config override (repeatable)")
    n.add_argument("--command",
                   help="server command; $TB_PORT is the port to bind "
                        "(default: jupyter notebook)")
    n.add_argument("--port", type=int, default=0,
                   help="local proxy port (default: auto)")
    n.add_argument("--workdir", help="client workdir (default ~/.tony-tpu)")
    n.set_defaults(fn=_cmd_notebook)

    k = sub.add_parser("kill", help="force-kill a running application")
    k.add_argument("app_id")
    k.add_argument("--workdir", help="client workdir the job was "
                                     "submitted from (default ~/.tony-tpu)")
    k.set_defaults(fn=_cmd_kill)

    rc = sub.add_parser(
        "recover",
        help="restart a crashed coordinator from its session journal and "
             "re-adopt the surviving executors (blocks until the job "
             "finishes)")
    rc.add_argument("app_id")
    rc.add_argument("--workdir", help="client workdir the job was "
                                      "submitted from (default ~/.tony-tpu)")
    rc.add_argument("--history-root",
                    help="override tony.history.location from the frozen "
                         "config")
    rc.set_defaults(fn=_cmd_recover)

    rz = sub.add_parser(
        "resize",
        help="elastically resize a running job's gang — shrink drains "
             "and re-meshes without restarting (no burned epochs), grow "
             "re-admits members live (tony.elastic.* keys)")
    rz.add_argument("app_id")
    rz.add_argument("size", type=int, help="new gang size")
    rz.add_argument("--job", default="",
                    help="jobtype to resize (default: the configured "
                         "tony.elastic.jobtype)")
    rz.add_argument("--workdir", help="client workdir the job was "
                                      "submitted from (default ~/.tony-tpu)")
    rz.set_defaults(fn=_cmd_resize)

    mg = sub.add_parser(
        "migrate",
        help="live-migrate a running job's gang to another slice: "
             "fenced drain at a step barrier, final durable saves, "
             "relaunch/adopt on the target, restore with reshard — "
             "steps_lost==0 spot survival and defrag "
             "(tony.elastic.* keys; docs/operations.md Migration)")
    mg.add_argument("app_id")
    mg.add_argument("target",
                    help="destination node pool / slice name, e.g. "
                         "slice-1")
    mg.add_argument("--job", default="",
                    help="jobtype to migrate (default: the configured "
                         "tony.elastic.jobtype)")
    mg.add_argument("--workdir", help="client workdir the job was "
                                      "submitted from (default ~/.tony-tpu)")
    mg.set_defaults(fn=_cmd_migrate)

    st = sub.add_parser("status",
                        help="live report for a running job (falls back "
                             "to history for finished ones)")
    st.add_argument("app_id")
    st.add_argument("--workdir", help="client workdir the job was "
                                      "submitted from")
    st.add_argument("--history-root")
    st.set_defaults(fn=_cmd_status)

    tp = sub.add_parser(
        "top",
        help="live per-task utilization view for a running job "
             "(steps/s, MFU, HBM, RSS, heartbeat age — the gang's `top`)")
    tp.add_argument("app_id")
    tp.add_argument("--workdir", help="client workdir the job was "
                                      "submitted from (default ~/.tony-tpu)")
    tp.add_argument("--interval", type=float, default=2.0,
                    help="refresh cadence in seconds (default 2)")
    tp.add_argument("--once", action="store_true",
                    help="print one snapshot and exit (scripts/tests)")
    tp.set_defaults(fn=_cmd_top)

    pf = sub.add_parser(
        "profile",
        help="capture a device trace from a RUNNING job without "
             "restarting it: the target task arms jax.profiler at its "
             "next step boundary for N steps; the artifact lands under "
             "the job dir (portal /profile/<app>)")
    pf.add_argument("app_id")
    pf.add_argument("--steps", type=int, default=0,
                    help="steps to capture (default: "
                         "tony.profile.default-steps)")
    pf.add_argument("--task", default="",
                    help="task to profile, e.g. worker:1 (default: the "
                         "chief)")
    pf.add_argument("--workdir", help="client workdir the job was "
                                      "submitted from (default ~/.tony-tpu)")
    pf.add_argument("--timeout", type=float, default=120.0,
                    help="seconds to wait for the capture (default 120)")
    pf.add_argument("--interval", type=float, default=1.0,
                    help="status poll cadence in seconds")
    pf.set_defaults(fn=_cmd_profile)

    bn = sub.add_parser(
        "bench",
        help="bench utilities: `bench diff <base.json> <candidate.json>` "
             "compares headline + per-phase numbers with a tolerance and "
             "exits nonzero on regression (the BENCH_r* gate)")
    bn_sub = bn.add_subparsers(dest="bench_cmd", required=True)
    bd = bn_sub.add_parser("diff", help="compare two bench jsons")
    bd.add_argument("base")
    bd.add_argument("candidate")
    bd.add_argument("--tolerance", type=float, default=0.10)
    bd.add_argument("--json", action="store_true")
    bd.set_defaults(fn=_cmd_bench)

    tr = sub.add_parser(
        "trace",
        help="export a job's control-plane trace as Chrome/Perfetto "
             "trace_events JSON (submit → rendezvous → first step → "
             "teardown, one stitched tree); --fleet <fleet_dir> "
             "exports the WHOLE pool — queue spans, grants, every "
             "job's lifecycle, preempt/grow-back resizes — on one "
             "timeline under the shared fleet trace id")
    tr.add_argument("app_id", nargs="?", default="",
                    help="application id (omit with --fleet)")
    tr.add_argument("--fleet", metavar="FLEET_DIR", default="",
                    help="export a fleet dir's stitched pool-wide "
                         "trace instead of one job's")
    tr.add_argument("--history-root")
    tr.add_argument("--out", help="write JSON here instead of stdout")
    tr.add_argument("--cold-start", action="store_true",
                    help="print the per-phase submit→first-step "
                         "breakdown (stage/provision/spawn/register/"
                         "launch/user_boot) instead of the full trace")
    tr.set_defaults(fn=_cmd_trace)

    dg = sub.add_parser(
        "diagnose",
        help="why did my job die: verdict category, blamed task, "
             "evidence, traceback/stack-dump excerpts, causal timeline "
             "(post-hoc on history; live jobs get a provisional read)")
    dg.add_argument("app_id")
    dg.add_argument("--history-root")
    dg.add_argument("--json", action="store_true",
                    help="print the raw incident.json document")
    dg.add_argument("--fresh", action="store_true",
                    help="re-run the rule engine even when the "
                         "coordinator already wrote incident.json")
    dg.set_defaults(fn=_cmd_diagnose)

    al = sub.add_parser(
        "alerts",
        help="SLO/alert state for a job: live rule-engine rows from a "
             "running coordinator, or the journaled REC_ALERT "
             "transitions replayed for a finished/dead one")
    al.add_argument("app_id")
    al.add_argument("--workdir", help="client workdir the job was "
                                      "submitted from (default ~/.tony-tpu)")
    al.add_argument("--history-root")
    al.add_argument("--json", action="store_true")
    al.set_defaults(fn=_cmd_alerts)

    h = sub.add_parser("history", help="list finished jobs")
    h.add_argument("--history-root")
    h.set_defaults(fn=_cmd_history)

    e = sub.add_parser("events", help="dump a job's event stream")
    e.add_argument("app_id")
    e.add_argument("--history-root")
    e.set_defaults(fn=_cmd_events)

    lg = sub.add_parser("logs",
                        help="dump a job's per-task logs (yarn logs "
                             "analogue)")
    lg.add_argument("app_id")
    lg.add_argument("--task", help="only this task, e.g. worker:0")
    lg.add_argument("--history-root")
    lg.set_defaults(fn=_cmd_logs)

    po = sub.add_parser("portal", help="serve the history web portal")
    po.add_argument("--history-root")
    po.add_argument("--port", type=int, default=None)
    po.add_argument("--host", default="127.0.0.1",
                    help="bind address (default localhost; widen only "
                         "with --token set)")
    po.add_argument("--token", default=os.environ.get(
        "TONY_PORTAL_TOKEN", ""))
    po.set_defaults(fn=_cmd_portal)

    gc = sub.add_parser(
        "gcloud-gc",
        help="list/delete leaked tony-managed TPU nodes (the RM-reaper "
             "role for hard-crashed coordinators)")
    gc.add_argument("--project", required=True)
    gc.add_argument("--zone", required=True)
    gc.add_argument("--prefix", default="tony",
                    help="only nodes whose id starts with this "
                         "(tony.gcloud.node-prefix)")
    gc.add_argument("--delete", action="store_true",
                    help="actually delete (default: list only)")
    gc.add_argument("--api-endpoint", default="",
                    help="Cloud TPU API endpoint override (tests)")
    gc.add_argument("--poll-interval", type=float, default=5.0,
                    help="delete-operation poll cadence in seconds")
    gc.set_defaults(fn=_cmd_gcloud_gc)

    pl = sub.add_parser(
        "pool",
        help="warm executor pool: keep pre-spawned executors (python + "
             "tony_tpu + jax + compile cache warm) that submits adopt "
             "for sub-2s resubmit (tony.pool.* keys)")
    pl.add_argument("action", choices=("start", "stop", "status"))
    pl.add_argument("--dir", help="pool directory (default: "
                                  "<workdir>/pool)")
    pl.add_argument("--workdir")
    pl.add_argument("--size", type=int, default=None,
                    help="warm executors to keep ready "
                         "(default: tony.pool.size)")
    pl.add_argument("--preload", default=None,
                    help="modules to pre-import per worker "
                         "(default: tony.pool.preload)")
    pl.add_argument("--conf-file")
    pl.add_argument("--conf", action="append", metavar="K=V")
    pl.set_defaults(fn=_cmd_pool)

    fl = sub.add_parser(
        "fleet",
        help="persistent multi-job gang scheduler over a shared slice "
             "pool: priorities, per-tenant quotas, bin-packing, "
             "preempt-to-reclaim via elastic shrink (tony.fleet.* keys; "
             "docs/operations.md Multi-tenancy)")
    fl_sub = fl.add_subparsers(dest="fleet_cmd", required=True)
    fs = fl_sub.add_parser("start", help="spawn the fleet daemon "
                                         "detached and wait for its "
                                         "endpoint")
    fs.add_argument("--dir", help="fleet state dir (default: "
                                  "<workdir>/fleet)")
    fs.add_argument("--workdir")
    fs.add_argument("--slices", type=int, default=None,
                    help="pool slices (default: tony.fleet.slices)")
    fs.add_argument("--hosts-per-slice", type=int, default=None,
                    help="hosts per slice (default: "
                         "tony.fleet.hosts-per-slice)")
    fs.add_argument("--quotas", default=None,
                    help="tenant=hosts,... (default: tony.fleet.quotas)")
    fs.add_argument("--pool-dir", default=None,
                    help="warm executor pool for every grant "
                         "(default: tony.fleet.pool-dir)")
    fs.add_argument("--cache-root", default=None,
                    help="per-model shared compile-cache root "
                         "(default: tony.fleet.compile-cache-root)")
    fs.add_argument("--recover", action="store_true",
                    help="replay the fleet journal and resume the same "
                         "queue state (after a daemon crash)")
    fs.add_argument("--conf-file")
    fs.add_argument("--conf", action="append", metavar="K=V")
    fs.set_defaults(fn=_cmd_fleet)
    for name, hlp in (("stop", "stop the daemon (running jobs keep "
                               "running)"),
                      ("status", "one scheduler snapshot"),
                      ("top", "live scheduler view (pool occupancy, "
                              "tenants, queue waits)")):
        fx = fl_sub.add_parser(name, help=hlp)
        fx.add_argument("--dir")
        fx.add_argument("--workdir")
        fx.add_argument("--conf-file")
        fx.add_argument("--conf", action="append", metavar="K=V")
        if name == "top":
            fx.add_argument("--interval", type=float, default=2.0)
            fx.add_argument("--once", action="store_true")
        fx.set_defaults(fn=_cmd_fleet)
    fb = fl_sub.add_parser(
        "submit",
        help="queue a job through the fleet: the policy engine grants "
             "it hosts (or queues it behind priorities/quotas) and the "
             "daemon runs it through the ordinary submit stack")
    fb.add_argument("--dir")
    fb.add_argument("--workdir")
    fb.add_argument("--tenant", required=True)
    fb.add_argument("--hosts", type=int, required=True,
                    help="gang size in pool hosts "
                         "(becomes tony.worker.instances)")
    fb.add_argument("--priority", type=int, default=0,
                    help="higher preempts lower (default 0)")
    fb.add_argument("--min-hosts", type=int, default=0,
                    help="elastic shrink floor; >0 marks the job "
                         "preemptible via elastic resize (never killed)")
    fb.add_argument("--model", default="",
                    help="model key for the shared compile-cache mount "
                         "(tenants sharing a model share warm compiles)")
    fb.add_argument("--follow", action="store_true",
                    help="poll until the job reaches a terminal state")
    fb.add_argument("--conf-file", help="job config (json/yaml)")
    fb.add_argument("--conf", action="append", metavar="K=V",
                    help="job config override (repeatable)")
    fb.set_defaults(fn=_cmd_fleet)
    fc = fl_sub.add_parser("cancel", help="cancel a queued or running "
                                          "fleet job")
    fc.add_argument("job")
    fc.add_argument("--dir")
    fc.add_argument("--workdir")
    fc.add_argument("--conf-file")
    fc.add_argument("--conf", action="append", metavar="K=V")
    fc.set_defaults(fn=_cmd_fleet)
    fm = fl_sub.add_parser(
        "migrate",
        help="live-migrate a RUNNING fleet job to another slice by "
             "hand (defrag, pre-maintenance evacuation): the daemon "
             "drives the job's own drain→move→reshard migration and "
             "re-books the pool — the policy engine also plans these "
             "itself on fragmentation and reclaim notices")
    fm.add_argument("job")
    fm.add_argument("target", type=int, help="destination slice index")
    fm.add_argument("--dir")
    fm.add_argument("--workdir")
    fm.add_argument("--conf-file")
    fm.add_argument("--conf", action="append", metavar="K=V")
    fm.set_defaults(fn=_cmd_fleet)
    fe = fl_sub.add_parser(
        "explain",
        help="why is my job queued: the causal hold timeline — every "
             "scheduler decision transition (quota / capacity / "
             "fragmentation / priority-held / preempt-wait) with the "
             "blocking jobs/tenants named; falls back to journal "
             "replay when the daemon is down")
    fe.add_argument("job")
    fe.add_argument("--dir")
    fe.add_argument("--workdir")
    fe.add_argument("--json", action="store_true",
                    help="print the raw decision/milestone document")
    fe.add_argument("--conf-file")
    fe.add_argument("--conf", action="append", metavar="K=V")
    fe.set_defaults(fn=_cmd_fleet)
    fd = fl_sub.add_parser(
        "diagnose",
        help="fleet-level rule engine over the goodput ledger + "
             "decision records: STARVATION / QUOTA_SATURATED / "
             "FRAGMENTATION / PREEMPT_STORM / POOL_COLD / "
             "FLEET_HEALTHY, evidence-backed (works offline from the "
             "fleet dir; docs/operations.md 'Fleet triage')")
    fd.add_argument("--dir")
    fd.add_argument("--workdir")
    fd.add_argument("--json", action="store_true",
                    help="print the raw fleet.incident.json document")
    fd.add_argument("--conf-file")
    fd.add_argument("--conf", action="append", metavar="K=V")
    fd.set_defaults(fn=_cmd_fleet)
    fw = fl_sub.add_parser(
        "whatif",
        help="fleet time machine: replay the recorded journal through "
             "the real policy engine under counterfactual quotas / "
             "priorities / pool shape and diff goodput, queue waits "
             "and per-tenant hold seconds against the recorded run — "
             "parity-gated, fully offline (docs/operations.md "
             "'Capacity planning and what-if')")
    fw.add_argument("--set", action="append", default=[],
                    metavar="K=V",
                    help="override a tony.fleet.* knob in the replay "
                         "(quotas, slices, hosts-per-slice, "
                         "sim-preemption/defrag/restore; also the "
                         "quota.<tenant> / priority.<job> / pool "
                         "shorthands)")
    fw.add_argument("--quota", action="append", default=[],
                    metavar="TENANT=N",
                    help="counterfactual host quota for one tenant")
    fw.add_argument("--pool", default="",
                    metavar="SxH", help="counterfactual pool shape, "
                    "e.g. 4x8 = 4 slices of 8 hosts")
    fw.add_argument("--priority", action="append", default=[],
                    metavar="JOB=P",
                    help="counterfactual priority for one recorded job")
    fw.add_argument("--sweep", action="append", default=[],
                    metavar="K=a,b,c",
                    help="sweep one key over a value grid (repeat for "
                         "a cartesian product; max 64 combinations)")
    fw.add_argument("--expect-parity", action="store_true",
                    help="exit 1 unless the parity gate reproduces the "
                         "recorded sequence bit-for-bit")
    fw.add_argument("--dir")
    fw.add_argument("--workdir")
    fw.add_argument("--json", action="store_true",
                    help="print the raw whatif report document")
    fw.add_argument("--conf-file")
    fw.add_argument("--conf", action="append", metavar="K=V")
    fw.set_defaults(fn=_cmd_fleet)
    fco = fl_sub.add_parser(
        "cordon",
        help="pull one pool host out of placement by hand "
             "(pre-maintenance, suspected hardware); manual cordons "
             "never auto-expire — close with uncordon "
             "(docs/operations.md 'Host health')")
    fco.add_argument("host", help="pool host id, e.g. s0h3")
    fco.add_argument("--reason", default="", help="recorded in the "
                     "health journal and `fleet health` evidence")
    fco.add_argument("--dir")
    fco.add_argument("--workdir")
    fco.add_argument("--conf-file")
    fco.add_argument("--conf", action="append", metavar="K=V")
    fco.set_defaults(fn=_cmd_fleet)
    fun = fl_sub.add_parser(
        "uncordon", help="return a cordoned host to the placement pool")
    fun.add_argument("host")
    fun.add_argument("--dir")
    fun.add_argument("--workdir")
    fun.add_argument("--conf-file")
    fun.add_argument("--conf", action="append", metavar="K=V")
    fun.set_defaults(fn=_cmd_fleet)
    fh = fl_sub.add_parser(
        "health",
        help="the host-health ledger: per-host state/score/evidence, "
             "the current cordon set and any sick slices "
             "(tony.health.* keys)")
    fh.add_argument("--dir")
    fh.add_argument("--workdir")
    fh.add_argument("--json", action="store_true",
                    help="print the raw ledger document")
    fh.add_argument("--conf-file")
    fh.add_argument("--conf", action="append", metavar="K=V")
    fh.set_defaults(fn=_cmd_fleet)
    fa = fl_sub.add_parser(
        "alerts",
        help="fleet-scope SLO/alert state: live rule-engine rows from "
             "a running daemon, or the journaled REC_FLEET_ALERT "
             "transitions replayed for a dead one")
    fa.add_argument("--dir")
    fa.add_argument("--workdir")
    fa.add_argument("--json", action="store_true",
                    help="print the raw alerts document")
    fa.add_argument("--conf-file")
    fa.add_argument("--conf", action="append", metavar="K=V")
    fa.set_defaults(fn=_cmd_fleet)

    ln = sub.add_parser(
        "lint",
        help="run tonylint, the project invariant checker: conf-key / "
             "fault-site / event-type / rpc-parity registries plus the "
             "durable-write, clock, span, thread and lock disciplines "
             "(docs/development.md). Exits nonzero on findings.")
    ln.add_argument("--json", action="store_true",
                    help="emit findings as JSON")
    ln.add_argument("--rule", action="append", metavar="RULE",
                    help="run only this rule id (repeatable)")
    ln.add_argument("--root", default=None,
                    help="repo root to lint (default: this install)")
    ln.add_argument("--list", dest="list_rules", action="store_true",
                    help="list rule ids and exit")
    ln.set_defaults(fn=_cmd_lint)

    ck = sub.add_parser(
        "check",
        help="verify a finished job's artifacts against the "
             "control-plane protocol invariants: journal gen/mgen "
             "monotonicity, resize pairing, epoch fences, terminal-"
             "state discipline, span-tree closure, phase sums, and the "
             "metrics registry (docs/development.md). Run it BEFORE "
             "diagnose: a protocol violation means the artifacts "
             "themselves may be lying. Exits nonzero on violations.")
    ck.add_argument("target",
                    help="an app id (resolved under the history root) "
                         "or a job-dir path")
    ck.add_argument("--history-root")
    ck.add_argument("--json", action="store_true",
                    help="emit the report as JSON")
    ck.set_defaults(fn=_cmd_check)

    ch = sub.add_parser(
        "chaos",
        help="the seeded multi-fault chaos engine (tony_tpu/chaos/): "
             "plan correlated-failure schedules from one seed, run "
             "them against the in-process control plane under the "
             "invariant ladder, replay any artifact bit-identically, "
             "and delta-debug a failing schedule to its minimal repro "
             "(docs/operations.md \u00a7 Chaos drills).")
    ch_sub = ch.add_subparsers(dest="chaos_cmd", required=True)
    cr = ch_sub.add_parser(
        "run", help="sweep N seeded schedules; exit nonzero if any "
                    "run violates the invariant ladder")
    cr.add_argument("--seed", type=int, default=0,
                    help="sweep seed: same seed, same schedules, "
                         "same per-call fault decisions (default 0)")
    cr.add_argument("--schedules", type=int, default=20,
                    help="how many schedules to plan and run")
    cr.add_argument("--suite",
                    choices=["e2e", "fleet", "migrate", "health"],
                    default=None,
                    help="restrict to one suite (default: round-robin "
                         "across all of them)")
    cr.add_argument("--out", default="chaos-artifacts",
                    help="artifact directory (one JSON per schedule)")
    cr.add_argument("--fail-fast", action="store_true",
                    help="stop at the first ladder violation")
    cr.set_defaults(fn=_cmd_chaos_run)
    cp = ch_sub.add_parser(
        "replay", help="re-plan + re-run one artifact's schedule; "
                       "proves planner determinism, then compares the "
                       "ladder verdict against the recording")
    cp.add_argument("artifact", help="a chaos artifact JSON path")
    cp.add_argument("--out", default="chaos-artifacts",
                    help="artifact directory for the re-run")
    cp.set_defaults(fn=_cmd_chaos_replay)
    cs = ch_sub.add_parser(
        "shrink", help="ddmin a FAILING artifact's schedule to the "
                       "1-minimal injection set that still fails; "
                       "saves the minimal repro as a new artifact")
    cs.add_argument("artifact", help="a failing chaos artifact JSON")
    cs.add_argument("--out", default="chaos-artifacts",
                    help="artifact directory for shrink runs")
    cs.add_argument("--max-runs", type=int, default=60,
                    help="shrink budget: predicate re-runs (default 60)")
    cs.add_argument("--note", default="",
                    help="provenance note stored in the shrunk artifact")
    cs.set_defaults(fn=_cmd_chaos_shrink)

    return p


def main(argv: Optional[List[str]] = None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s")
    args = build_parser().parse_args(argv)
    from tony_tpu.conf.config import ConfigError

    try:
        return args.fn(args)
    except (ConfigError, FileNotFoundError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
