import sys

from tony_tpu.cli.main import main

if __name__ == "__main__":
    sys.exit(main())
