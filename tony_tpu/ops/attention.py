"""Blockwise (flash) attention as Pallas TPU kernels.

Memory-bound attention is the canonical HBM-bandwidth problem
(pallas_guide.md): materializing the [S, S] score matrix is O(S²) HBM
traffic, while the blockwise online-softmax formulation streams K/V tiles
through VMEM and keeps the running (max, sum, acc) state on-chip, so HBM
traffic stays O(S·D). Forward and backward are custom kernels under a
``jax.custom_vjp``; the forward saves only O and the row logsumexp L.

TPU-first design points (round-3 rework):

- **GQA is zero-copy.** K/V stay at their native ``[B, H_kv, S, D]`` shape;
  the q→kv head mapping happens in the BlockSpec index maps (``h // g``), so
  repeated heads cost no extra HBM footprint or bandwidth. The dk/dv grid
  folds the ``g`` group members into its innermost loop and accumulates in
  VMEM scratch.
- **Per-row stats are near-minimal.** lse/delta are ``[B, H, 8, S]`` f32 —
  the 8-sublane-broadcast layout (32 B/row, the smallest tileable form: the
  last two dims must tile (8, 128)) — not the ``[·, S, 128]``
  lane-broadcast layout of jax's bundled kernel (512 B/row; measurable at
  long context).
- **Matmuls run at native MXU rate.** Inputs keep their dtype (bf16 stays
  bf16) with ``preferred_element_type=f32`` accumulation; softmax state is
  f32 on-chip.
- **Causal tiles are skipped in the DMA, not just the ALU.** Index maps
  clamp fully-masked tiles to the previous fetch, so Pallas's pipeline
  skips the copy (revisited blocks are not re-fetched).

Public layout is ``[batch, seq, heads, head_dim]`` (the layout the models
use); kernels run on ``[B, H, S, D]`` views. On non-TPU backends the kernels
run in Pallas interpret mode so the exact same code path is unit-tested on
the virtual CPU mesh (SURVEY.md §4 test strategy).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30
# Stats (lse/delta) sublane broadcast factor: min f32 tile is (8, 128), so
# a per-row float is stored as 8 identical sublanes over lanes=seq.
STAT_SUB = 8
# Default flash tile size, from the v5e sweeps documented on
# flash_attention: shared by every public attention entry point (flash,
# flash_with_lse, ring, ulysses) so a re-sweep updates one constant.
DEFAULT_BLOCK = 1024


def _prec(x):
    """Dot precision: TPU DEFAULT multiplies in bf16 (one MXU pass) — right
    for bf16 inputs, silently lossy for f32 ones. f32 inputs (the oracle /
    unit-test path) get HIGHEST (true f32 passes) so the kernel is exact
    where the caller asked for f32."""
    return (jax.lax.Precision.HIGHEST if x.dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)


def _load2d(ref, block_idx, block_rows, seq):
    """Load a [1, 1, block, d] block with out-of-range rows zeroed, keeping
    the stored dtype (bf16 in → bf16 out, so dots hit the MXU at full rate).
    Pallas pads partial edge blocks with undefined memory (NaN in interpret
    mode); a zero row is inert in every matmul below, undefined is not.
    When ``seq`` divides the block the guard compiles away entirely — the
    production path pays zero VPU passes here."""
    x = ref[0, 0]
    if seq % block_rows == 0:
        return x
    rows = block_idx * block_rows + jax.lax.broadcasted_iota(
        jnp.int32, x.shape, 0)
    return jnp.where(rows < seq, x, jnp.zeros_like(x))


def _load_stat(ref, block_idx, block_rows, seq):
    """Load a per-row statistic block [1, 1, STAT_SUB, block] (identical
    sublanes — see _finalize) as a [block, 1] COLUMN vector, zero past
    ``seq``. Column (sublane) orientation matters: the stats broadcast
    against the [bq, bk] score tile along lanes, and handing Mosaic a lane
    vector here would cost a lane→sublane relayout on every tile."""
    x = jnp.transpose(ref[0, 0][:1, :])        # [block, 1]
    if seq % block_rows == 0:
        return x
    rows = block_idx * block_rows + jax.lax.broadcasted_iota(
        jnp.int32, x.shape, 0)
    return jnp.where(rows < seq, x, 0.0)


def _store_stat(ref, col):
    """Store a [block, 1] column stat as the [STAT_SUB, block] sublane-
    broadcast block."""
    ref[0, 0] = jnp.broadcast_to(jnp.transpose(col), ref.shape[2:])


def _last_valid_kj(i, block_q, block_k):
    """Last k-block index with any unmasked causal element for q-tile
    ``i``. Single source of truth for BOTH the kernels' compute guards and
    the index-map DMA clamps — they must never disagree."""
    return (i * block_q + block_q - 1) // block_k


def _first_valid_qi(j, block_q, block_k):
    """First q-block index with any unmasked causal element for k-tile
    ``j`` (identity: ceil((j·bk − bq + 1)/bq) == floor(j·bk/bq))."""
    return (j * block_k) // block_q


def _mask_scores(s, qi, kj, block_q, block_k, causal, seq_q, seq_k):
    """Set invalid scores to NEG_INF so they vanish through exp().

    VPU passes over the [bq, bk] score tile are the flash bottleneck at
    small head_dim, so the mask is ONE broadcast compare + ONE select built
    from 1-D iotas ([bq,1] vs [1,bk] — register-cheap), and the
    sequence-edge guards (grid padding when seq % block != 0) are emitted
    only for ragged shapes: the production path (divisible seq) pays 2
    passes for causal, 0 for non-causal.

    Returns (masked s, valid) — ``valid`` is None when only the causal
    compare ran (no padded rows/cols exist, so exp(masked) needs no extra
    zeroing)."""
    rows = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (s.shape[0], 1), 0)
    cols = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (1, s.shape[1]), 1)
    ragged = bool(seq_q % block_q) or bool(seq_k % block_k)
    valid = None
    if ragged:
        # Padded-q rows are masked too so backward passes can't scatter
        # garbage into dk/dv (forward writes of padded rows are dropped).
        valid = (cols < seq_k) & (rows < seq_q)
        if causal:
            valid = valid & (rows >= cols)
    elif causal:
        valid = rows >= cols
    if valid is None:
        return s, None
    s = jnp.where(valid, s, NEG_INF)
    return s, (valid if ragged else None)


def reference_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = True,
                        scale: Optional[float] = None) -> jax.Array:
    """Plain XLA attention ([B,S,H,D] layout) — the correctness oracle.
    Einsums run at HIGHEST precision: on TPU the DEFAULT is bf16 multiplies,
    which would make the oracle less accurate than the kernel under test."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   precision=_prec(q)).astype(jnp.float32) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                      precision=_prec(v))


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *scratch,
                scale: float, causal: bool, block_q: int, block_k: int,
                num_k_blocks: int, seq_q: int, seq_k: int,
                fused_rowsum: bool):
    if fused_rowsum:
        m_scr, acc_scr = scratch
        l_scr = None
    else:
        m_scr, l_scr, acc_scr = scratch
    qi = pl.program_id(2)
    kj = pl.program_id(3)

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        acc_scr[:] = jnp.zeros_like(acc_scr)
        if not fused_rowsum:
            l_scr[:] = jnp.zeros_like(l_scr)

    # Causal: skip fully-masked tiles (k strictly after the q tile's end).
    run = True
    if causal:
        run = kj <= _last_valid_kj(qi, block_q, block_k)

    @pl.when(run)
    def _compute():
        q = _load2d(q_ref, qi, block_q, seq_q)    # [block_q, d]
        k = _load2d(k_ref, kj, block_k, seq_k)    # [block_k, d]
        v = _load2d(v_ref, kj, block_k, seq_k)    # [block_k, d]
        # Scale folded into the [·, d] q block — 8–16× fewer elements than
        # a post-hoc pass over the [bq, bk] score tile.
        qs = q * jnp.asarray(scale, q.dtype)
        s = jax.lax.dot_general(
            qs, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_prec(q))                   # [block_q, block_k]
        s, _ = _mask_scores(s, qi, kj, block_q, block_k, causal, seq_q,
                            seq_k)
        # All row stats stay [block_q, 1] COLUMN vectors: reductions use
        # keepdims and the scratch is (block_q, 1), so no lane↔sublane
        # relayout ever happens on the hot path (1-D lane vectors with
        # [:, None] broadcasts cost a relayout per tile).
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        if fused_rowsum:
            # The row-sum rides the MXU: a ones column appended to v makes
            # the pv dot produce [o_partial | l_partial] in one accumulator
            # — free while d+1 fits the 128-wide MXU/lane tile, deleting
            # the VPU sum-reduce pass over the score tile. (At d >= 128
            # the extra column would pad to a second lane tile, doubling
            # accumulator VMEM — the plain reduce is used instead.)
            v1 = jnp.concatenate(
                [v, jnp.ones((v.shape[0], 1), v.dtype)], axis=1)
            acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot(
                p.astype(v.dtype), v1, preferred_element_type=jnp.float32,
                precision=_prec(v))
        else:
            l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
            acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot(
                p.astype(v.dtype), v, preferred_element_type=jnp.float32,
                precision=_prec(v))
        m_scr[:] = m_new

    @pl.when(kj == num_k_blocks - 1)
    def _finalize():
        if fused_rowsum:
            acc = acc_scr[:]
            l = jnp.maximum(acc[:, -1:], 1e-30)
            o_ref[0, 0] = (acc[:, :-1] / l).astype(o_ref.dtype)
        else:
            l = jnp.maximum(l_scr[:], 1e-30)
            o_ref[0, 0] = (acc_scr[:] / l).astype(o_ref.dtype)
        _store_stat(lse_ref, m_scr[:] + jnp.log(l))


# ---------------------------------------------------------------------------
# Backward kernels (standard flash backward, two passes)
# ---------------------------------------------------------------------------
def _p_block(s, lse, qi, kj, block_q, block_k, causal, seq_q, seq_k):
    """exp(s − lse) with NEG_INF masking (causal entries vanish through the
    exp). Ragged shapes additionally zero p explicitly: padded lse/do reads
    are undefined memory on TPU, so exp(s − lse) can't be trusted there —
    for divisible shapes that where() is statically elided."""
    sm, valid = _mask_scores(s, qi, kj, block_q, block_k, causal, seq_q,
                             seq_k)
    p = jnp.exp(sm - lse)                       # lse is [bq, 1]
    if valid is not None:
        p = jnp.where(valid, p, 0.0)
    return p


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   acc_scr, *, scale: float, causal: bool, block_q: int,
                   block_k: int, num_k_blocks: int, seq_q: int, seq_k: int):
    qi = pl.program_id(2)
    kj = pl.program_id(3)

    @pl.when(kj == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = True
    if causal:
        run = kj <= _last_valid_kj(qi, block_q, block_k)

    @pl.when(run)
    def _compute():
        q = _load2d(q_ref, qi, block_q, seq_q)
        k = _load2d(k_ref, kj, block_k, seq_k)
        v = _load2d(v_ref, kj, block_k, seq_k)
        do = _load2d(do_ref, qi, block_q, seq_q)
        lse = _load_stat(lse_ref, qi, block_q, seq_q)
        delta = _load_stat(delta_ref, qi, block_q, seq_q)
        # One scaled copy of the [·, d] k block serves both dots:
        # s = q·(k·scale)ᵀ and dq += ds_hat·(k·scale), where
        # ds_hat = p·(dp − delta) — no [bq, bk]-sized scale pass.
        ks = k * jnp.asarray(scale, k.dtype)
        s = jax.lax.dot_general(
            q, ks, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_prec(q))
        p = _p_block(s, lse, qi, kj, block_q, block_k, causal, seq_q,
                     seq_k)                                 # [bq, bk]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_prec(v))
        ds = (p * (dp - delta)).astype(k.dtype)
        acc_scr[:] += jax.lax.dot(ds, ks,
                                  preferred_element_type=jnp.float32,
                                  precision=_prec(k))

    @pl.when(kj == num_k_blocks - 1)
    def _finalize():
        dq_ref[0, 0] = acc_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, scale: float,
                    causal: bool, block_q: int, block_k: int,
                    num_q_blocks: int, num_inner: int, seq_q: int,
                    seq_k: int):
    kj = pl.program_id(2)
    t = pl.program_id(3)          # folds (group member, q block)
    qi = t % num_q_blocks

    @pl.when(t == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = True
    if causal:
        # q tiles strictly before the k tile's start contribute nothing.
        run = qi >= _first_valid_qi(kj, block_q, block_k)

    @pl.when(run)
    def _compute():
        q = _load2d(q_ref, qi, block_q, seq_q)
        k = _load2d(k_ref, kj, block_k, seq_k)
        v = _load2d(v_ref, kj, block_k, seq_k)
        do = _load2d(do_ref, qi, block_q, seq_q)
        lse = _load_stat(lse_ref, qi, block_q, seq_q)
        delta = _load_stat(delta_ref, qi, block_q, seq_q)
        # One scaled [·, d] q block serves s = (q·scale)·kᵀ and
        # dk += ds_hatᵀ·(q·scale) — no [bq, bk]-sized scale pass.
        qs = q * jnp.asarray(scale, q.dtype)
        s = jax.lax.dot_general(
            qs, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_prec(q))
        p = _p_block(s, lse, qi, kj, block_q, block_k, causal, seq_q,
                     seq_k)                                 # [bq, bk]
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_prec(do))
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_prec(v))
        ds = (p * (dp - delta)).astype(q.dtype)
        dk_scr[:] += jax.lax.dot_general(
            ds, qs, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_prec(q))

    @pl.when(t == num_inner - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call plumbing
# ---------------------------------------------------------------------------
def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _round_up(x: int, m: int) -> int:
    """Blocks must honour TPU sublane tiling (8 f32 / 16 bf16 rows);
    a block clamped to a ragged seq length would not lower."""
    return -(-x // m) * m


def _fwd_impl(q, k, v, scale, causal, block_q, block_k, out_dtype=None):
    b, h, sq, d = q.shape
    hk = k.shape[1]
    g = h // hk
    sk = k.shape[2]
    # q blocks round to 128: block_q is the stats blocks' LANE dim, which
    # must be a multiple of 128 (k blocks only ever sit on sublanes → 16).
    block_q = min(block_q, _round_up(sq, 128))
    block_k = min(block_k, _round_up(sk, 16))
    nq = pl.cdiv(sq, block_q)
    nk = pl.cdiv(sk, block_k)
    from jax.experimental.pallas import tpu as pltpu

    def kv_j(i, j):
        # Clamp fully-masked causal tiles to the previous fetch so the
        # pipeline skips the DMA (revisited blocks are not re-fetched).
        return jnp.minimum(j, _last_valid_kj(i, block_q, block_k)) \
            if causal else j

    fused_rowsum = d < 128
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, num_k_blocks=nk, seq_q=sq, seq_k=sk,
        fused_rowsum=fused_rowsum)
    o, lse = pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b, h, i, j: (b, h // g, kv_j(i, j), 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b, h, i, j: (b, h // g, kv_j(i, j), 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, STAT_SUB, block_q),
                         lambda b, h, i, j: (b, h, 0, i)),
        ],
        out_shape=[
            # out_dtype=f32 hands the caller the kernel's own f32
            # accumulator unrounded — ring attention threads it through
            # hops so error stays flat in sp degree (ops/ring.py).
            jax.ShapeDtypeStruct((b, h, sq, d), out_dtype or q.dtype),
            jax.ShapeDtypeStruct((b, h, STAT_SUB, sq), jnp.float32),
        ],
        scratch_shapes=(
            [pltpu.VMEM((block_q, 1), jnp.float32),
             pltpu.VMEM((block_q, d + 1), jnp.float32)]
            if fused_rowsum else
            [pltpu.VMEM((block_q, 1), jnp.float32),
             pltpu.VMEM((block_q, 1), jnp.float32),
             pltpu.VMEM((block_q, d), jnp.float32)]),
        interpret=_interpret(),
    )(q, k, v)
    return o, lse


def _bwd_impl(q, k, v, o, lse, do, scale, causal, block_q, block_k,
              dlse=None):
    b, h, sq, d = q.shape
    hk = k.shape[1]
    g = h // hk
    sk = k.shape[2]
    block_q = min(block_q, _round_up(sq, 128))
    block_k = min(block_k, _round_up(sk, 16))
    nq = pl.cdiv(sq, block_q)
    nk = pl.cdiv(sk, block_k)
    from jax.experimental.pallas import tpu as pltpu
    delta_rows = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32),
                         axis=-1)                    # [B, H, S]
    if dlse is not None:
        # lse cotangent (flash_attention_with_lse): ∂lse_i/∂s_ij = p_ij, so
        # the extra term folds into the existing ds = p·(dp − delta) as
        # ds = p·(dp − (delta − dlse)) — one subtract, zero kernel changes.
        delta_rows = delta_rows - dlse.astype(jnp.float32)
    delta = jnp.broadcast_to(
        delta_rows[:, :, None, :],
        (b, h, STAT_SUB, sq))                        # sublane-bcast like lse

    def kv_j(i, j):
        return jnp.minimum(j, _last_valid_kj(i, block_q, block_k)) \
            if causal else j

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_k_blocks=nk,
                          seq_q=sq, seq_k=sk),
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b, h, i, j: (b, h // g, kv_j(i, j), 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b, h, i, j: (b, h // g, kv_j(i, j), 0)),
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, STAT_SUB, block_q),
                         lambda b, h, i, j: (b, h, 0, i)),
            pl.BlockSpec((1, 1, STAT_SUB, block_q),
                         lambda b, h, i, j: (b, h, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda b, h, i, j: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    # dk/dv: one grid cell per kv head; the g q-head group members are
    # folded into the innermost loop (t = gi·nq + qi) and accumulated in
    # VMEM — repeated K/V is never materialized, in either direction.
    ni = g * nq

    def qh(hk_, t):
        return hk_ * g + t // nq

    def q_i(j, t):
        i = t % nq
        # First q-tile with any unmasked element for k-tile j (causal);
        # clamping masked tiles to it skips their DMA.
        return jnp.maximum(i, _first_valid_qi(j, block_q, block_k)) \
            if causal else i

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_q_blocks=nq,
                          num_inner=ni, seq_q=sq, seq_k=sk),
        grid=(b, hk, nk, ni),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b, hk_, j, t: (b, qh(hk_, t), q_i(j, t), 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b, hk_, j, t: (b, hk_, j, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b, hk_, j, t: (b, hk_, j, 0)),
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b, hk_, j, t: (b, qh(hk_, t), q_i(j, t), 0)),
            pl.BlockSpec((1, 1, STAT_SUB, block_q),
                         lambda b, hk_, j, t: (b, qh(hk_, t), 0, q_i(j, t))),
            pl.BlockSpec((1, 1, STAT_SUB, block_q),
                         lambda b, hk_, j, t: (b, qh(hk_, t), 0, q_i(j, t))),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b, hk_, j, t: (b, hk_, j, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b, hk_, j, t: (b, hk_, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hk, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b, hk, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Public API with custom VJP
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, scale, causal, block_q, block_k):
    o, _ = _fwd_impl(q, k, v, scale, causal, block_q, block_k)
    return o


def _flash_fwd(q, k, v, scale, causal, block_q, block_k):
    o, lse = _fwd_impl(q, k, v, scale, causal, block_q, block_k)
    return o, (q, k, v, o, lse)


def _flash_bwd(scale, causal, block_q, block_k, res, do):
    q, k, v, o, lse = res
    return _bwd_impl(q, k, v, o, lse, do, scale, causal, block_q, block_k)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_lse(q, k, v, scale, causal, block_q, block_k, out_dtype):
    o, lse = _fwd_impl(q, k, v, scale, causal, block_q, block_k, out_dtype)
    return o, lse[:, :, 0, :]


def _flash_lse_fwd(q, k, v, scale, causal, block_q, block_k, out_dtype):
    o, lse = _fwd_impl(q, k, v, scale, causal, block_q, block_k, out_dtype)
    return (o, lse[:, :, 0, :]), (q, k, v, o, lse)


def _flash_lse_bwd(scale, causal, block_q, block_k, out_dtype, res, cts):
    do, dlse = cts
    q, k, v, o, lse = res
    # With out_dtype=f32 the cotangent arrives f32 while q/k/v are bf16;
    # the backward kernels' matmuls must stay at the INPUT dtype's MXU
    # rate (and Mosaic wants matched operand dtypes) — the o·do delta
    # product inside _bwd_impl is f32 regardless, so no precision is
    # given up that the pre-out_dtype path had.
    return _bwd_impl(q, k, v, o, lse, do.astype(q.dtype), scale, causal,
                     block_q, block_k, dlse=dlse)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def _check_and_transpose(q, k, v, causal, scale):
    """Shared wrapper plumbing for the public entry points: validate the
    [B,S,H,D] shapes, default the scale, hand back [B,H,S,D] kernel
    views."""
    sq, h = q.shape[1], q.shape[2]
    hk = k.shape[2]
    if causal and sq != k.shape[1]:
        raise ValueError(
            f"causal flash attention requires seq_q == seq_k, got {sq} vs "
            f"{k.shape[1]} (the kernel's mask is top-left aligned; for "
            f"decode-style offsets use ring attention or causal=False with "
            f"an explicit mask)")
    if k.shape[2] != v.shape[2]:
        raise ValueError(f"k heads ({k.shape[2]}) != v heads "
                         f"({v.shape[2]})")
    if h % hk:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hk}")
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    return (q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3), scale)


def flash_attention_with_lse(q: jax.Array, k: jax.Array, v: jax.Array,
                             causal: bool = True,
                             scale: Optional[float] = None,
                             block_q: int = DEFAULT_BLOCK,
                             block_k: int = DEFAULT_BLOCK,
                             out_dtype=None):
    """Flash attention returning ``(o [B,S,H,D], lse [B,S,H] f32)``.

    ``lse`` is the per-row logsumexp of the (scaled, masked) scores — the
    online-softmax merge statistic. Two partial results over disjoint key
    sets combine exactly as::

        lse = logaddexp(lse_a, lse_b)
        o   = o_a·exp(lse_a − lse) + o_b·exp(lse_b − lse)

    which is what ring attention does across ``sp`` hops (``ops/ring.py``).
    Both outputs are differentiable (the lse cotangent rides the existing
    backward's delta statistic).

    ``out_dtype=jnp.float32`` returns the kernel's f32 accumulator
    unrounded (inputs and matmul rate unchanged) — for callers that merge
    partials and must not pay a bf16 rounding per merge."""
    qh, kh, vh, scale = _check_and_transpose(q, k, v, causal, scale)
    oh, lse = _flash_lse(qh, kh, vh, scale, causal, block_q, block_k,
                         out_dtype)
    return oh.transpose(0, 2, 1, 3), lse.transpose(0, 2, 1)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True, scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK,
                    block_k: int = DEFAULT_BLOCK) -> jax.Array:
    """Flash attention, layout ``[B, S, H, D]`` (GQA: H_kv may divide H).

    Differentiable (custom flash backward); accumulation in f32 regardless
    of input dtype (bf16 in, bf16 out, f32 softmax state on-chip), matmuls
    at the input dtype's MXU rate. GQA K/V are indexed in the BlockSpecs,
    never repeated.

    Default blocks (1024, 1024) come from v5e sweeps on the 317M flagship
    at seq 2048 (round 3, bf16 VMEM loads): 1024×1024 → 0.526 MFU
    end-to-end vs 0.477 at 512×512, 0.473 at 1024×512, 0.39 at ·×256;
    2048-wide k blocks exceed VMEM (the [bq, bk] f32 score tile is the
    limiter). Small tiles lose to per-tile VPU overhead at head_dim 64.
    The optimum HOLDS at long context (round-4 sweep, same model at seq
    8192, chunked-CE training end-to-end): 1024×1024 → 41.7k tok/s (MFU
    0.573) vs 40.3k at 512×1024 and 37.4k at 1024×512; 2048 in either
    dimension fails to compile (VMEM) at d=128. Blocks clamp to the
    actual (rounded-up) sequence, so short-seq/test calls are unaffected.
    """
    qh, kh, vh, scale = _check_and_transpose(q, k, v, causal, scale)
    oh = _flash(qh, kh, vh, scale, causal, block_q, block_k)
    return oh.transpose(0, 2, 1, 3)
