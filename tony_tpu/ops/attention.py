"""Blockwise (flash) attention as Pallas TPU kernels.

Memory-bound attention is the canonical HBM-bandwidth problem
(pallas_guide.md): materializing the [S, S] score matrix is O(S²) HBM
traffic, while the blockwise online-softmax formulation streams K/V tiles
through VMEM and keeps the running (max, sum, acc) state on-chip, so HBM
traffic stays O(S·D). Forward and backward are custom kernels under a
``jax.custom_vjp``; the forward saves only O and the row logsumexp L.

Public layout is ``[batch, seq, heads, head_dim]`` (the layout the models
use); kernels run per (batch·head) slice. On non-TPU backends the kernels
run in Pallas interpret mode so the exact same code path is unit-tested on
the virtual CPU mesh (SURVEY.md §4 test strategy).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30
# TPU lane width; per-row stats (lse, delta) are stored lane-broadcast as
# [B·H, S, 128] f32 — 128× the minimal HBM for those stats, the same layout
# jax's own TPU flash kernel uses (flash_attention.py MIN_BLOCK_SIZE scratch)
# because Mosaic wants the trailing two dims tileable to (8, 128). At 8B/
# long-context scale consider [B·H, S, 8] (min sublane tile) instead; the
# stats are ~d/128 of the O tensor either way (<1% of activation traffic).
LANES = 128


def _load2d(ref, block_idx, block_rows, seq):
    """Load a [1, block, d] block as f32 with out-of-range rows zeroed.
    Pallas pads partial edge blocks with undefined memory (NaN in interpret
    mode); a zero row is inert in every matmul below, undefined is not."""
    x = ref[0].astype(jnp.float32)
    rows = block_idx * block_rows + jax.lax.broadcasted_iota(
        jnp.int32, x.shape, 0)
    return jnp.where(rows < seq, x, 0.0)


def _load1d(ref, block_idx, block_rows, seq):
    """Load a per-row statistic stored as [1, block, LANES] (all lanes
    identical — see _finalize) and return the [block] vector, zero past
    ``seq``."""
    x = ref[0][:, 0]
    rows = block_idx * block_rows + jax.lax.iota(jnp.int32, x.shape[0])
    return jnp.where(rows < seq, x, 0.0)


def _mask_scores(s, qi, kj, block_q, block_k, causal, seq_q, seq_k):
    """Mask invalid scores: keys/queries past the true sequence ends (grid
    padding when seq % block != 0) and, for causal, keys after the query.
    Padded-q rows are masked too so backward passes can't scatter garbage
    into dk/dv (forward writes of padded rows are dropped by pallas)."""
    rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    cols = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    valid = (cols < seq_k) & (rows < seq_q)
    if causal:
        valid = valid & (rows >= cols)
    return jnp.where(valid, s, NEG_INF), valid


def reference_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = True,
                        scale: Optional[float] = None) -> jax.Array:
    """Plain XLA attention ([B,S,H,D] layout) — the correctness oracle."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale: float, causal: bool, block_q: int, block_k: int,
                num_k_blocks: int, seq_q: int, seq_k: int):
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal: skip fully-masked tiles (k strictly after the q tile's end).
    run = True
    if causal:
        run = kj * block_k <= qi * block_q + block_q - 1

    @pl.when(run)
    def _compute():
        q = _load2d(q_ref, qi, block_q, seq_q)    # [block_q, d]
        k = _load2d(k_ref, kj, block_k, seq_k)    # [block_k, d]
        v = _load2d(v_ref, kj, block_k, seq_k)    # [block_k, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [block_q, block_k]
        s, _ = _mask_scores(s, qi, kj, block_q, block_k, causal, seq_q,
                            seq_k)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=1)
        acc_scr[:] = acc_scr[:] * alpha[:, None] + jax.lax.dot(
            p, v, preferred_element_type=jnp.float32)
        m_scr[:] = m_new

    @pl.when(kj == num_k_blocks - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l[:, None]).astype(o_ref.dtype)
        # lse is [block_q, LANES] with identical lanes: Mosaic needs the
        # last two block dims tileable (8x128), so a 1-D [block_q] output
        # does not lower — same trick as jax's own TPU flash kernel.
        lse_ref[0] = jnp.broadcast_to((m_scr[:] + jnp.log(l))[:, None],
                                      lse_ref.shape[1:])


# ---------------------------------------------------------------------------
# Backward kernels (standard flash backward, two passes)
# ---------------------------------------------------------------------------
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   acc_scr, *, scale: float, causal: bool, block_q: int,
                   block_k: int, num_k_blocks: int, seq_q: int, seq_k: int):
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = True
    if causal:
        run = kj * block_k <= qi * block_q + block_q - 1

    @pl.when(run)
    def _compute():
        q = _load2d(q_ref, qi, block_q, seq_q)
        k = _load2d(k_ref, kj, block_k, seq_k)
        v = _load2d(v_ref, kj, block_k, seq_k)
        do = _load2d(do_ref, qi, block_q, seq_q)
        lse = _load1d(lse_ref, qi, block_q, seq_q)
        delta = _load1d(delta_ref, qi, block_q, seq_q)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        s, valid = _mask_scores(s, qi, kj, block_q, block_k, causal, seq_q,
                                seq_k)
        # Explicit zero (not just -inf scores): padded lse/do reads are
        # undefined memory on TPU, so exp(s - lse) can't be trusted there.
        p = jnp.where(valid, jnp.exp(s - lse[:, None]), 0.0)  # [bq, bk]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, bk]
        ds = p * (dp - delta[:, None]) * scale
        acc_scr[:] += jax.lax.dot(ds, k,
                                  preferred_element_type=jnp.float32)

    @pl.when(kj == num_k_blocks - 1)
    def _finalize():
        dq_ref[0] = acc_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, scale: float,
                    causal: bool, block_q: int, block_k: int,
                    num_q_blocks: int, seq_q: int, seq_k: int):
    kj = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = True
    if causal:
        # q tiles strictly before the k tile's start contribute nothing.
        run = qi * block_q + block_q - 1 >= kj * block_k

    @pl.when(run)
    def _compute():
        q = _load2d(q_ref, qi, block_q, seq_q)
        k = _load2d(k_ref, kj, block_k, seq_k)
        v = _load2d(v_ref, kj, block_k, seq_k)
        do = _load2d(do_ref, qi, block_q, seq_q)
        lse = _load1d(lse_ref, qi, block_q, seq_q)
        delta = _load1d(delta_ref, qi, block_q, seq_q)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        s, valid = _mask_scores(s, qi, kj, block_q, block_k, causal, seq_q,
                                seq_k)
        p = jnp.where(valid, jnp.exp(s - lse[:, None]), 0.0)  # [bq, bk]
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bk, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bk, d]

    @pl.when(qi == num_q_blocks - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call plumbing
# ---------------------------------------------------------------------------
def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _round_up(x: int, m: int) -> int:
    """Blocks must honour TPU sublane tiling (8 f32 / 16 bf16 rows);
    a block clamped to a ragged seq length would not lower."""
    return -(-x // m) * m


def _fwd_impl(q, k, v, scale, causal, block_q, block_k):
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, _round_up(sq, 16))
    block_k = min(block_k, _round_up(sk, 16))
    nq = pl.cdiv(sq, block_q)
    nk = pl.cdiv(sk, block_k)
    from jax.experimental.pallas import tpu as pltpu
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, num_k_blocks=nk, seq_q=sq, seq_k=sk)
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q,), jnp.float32),
            pltpu.VMEM((block_q,), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return o, lse


def _bwd_impl(q, k, v, o, lse, do, scale, causal, block_q, block_k):
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, _round_up(sq, 16))
    block_k = min(block_k, _round_up(sk, 16))
    nq = pl.cdiv(sq, block_q)
    nk = pl.cdiv(sk, block_k)
    from jax.experimental.pallas import tpu as pltpu
    delta = jnp.broadcast_to(
        jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32),
                axis=-1)[:, :, None],
        (bh, sq, LANES))                     # lane-broadcast like lse
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_k_blocks=nk,
                          seq_q=sq, seq_k=sk),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, LANES), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_q_blocks=nq,
                          seq_q=sq, seq_k=sk),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, LANES), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, LANES), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Public API with custom VJP
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, scale, causal, block_q, block_k):
    o, _ = _fwd_impl(q, k, v, scale, causal, block_q, block_k)
    return o


def _flash_fwd(q, k, v, scale, causal, block_q, block_k):
    o, lse = _fwd_impl(q, k, v, scale, causal, block_q, block_k)
    return o, (q, k, v, o, lse)


def _flash_bwd(scale, causal, block_q, block_k, res, do):
    q, k, v, o, lse = res
    return _bwd_impl(q, k, v, o, lse, do, scale, causal, block_q, block_k)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True, scale: Optional[float] = None,
                    block_q: int = 1024, block_k: int = 512) -> jax.Array:
    """Flash attention, layout ``[B, S, H, D]`` (GQA: H_kv may divide H).

    Differentiable (custom flash backward); numerics in f32 accumulation
    regardless of input dtype (bf16 in, bf16 out, f32 on-chip).

    Default blocks (1024, 512) come from a v5e sweep on the 317M flagship
    at seq 2048: 128×128 grid points are too small to amortize per-tile
    overhead at head_dim 64 (measured 14% MFU end-to-end vs 31.5% at
    1024×512; 1024×1024 regresses — VMEM pressure). Blocks clamp to the
    actual (rounded-up) sequence, so short-seq/test calls are unaffected.
    """
    b, sq, h, d = q.shape
    hk = k.shape[2]
    if causal and sq != k.shape[1]:
        raise ValueError(
            f"causal flash attention requires seq_q == seq_k, got {sq} vs "
            f"{k.shape[1]} (the kernel's mask is top-left aligned; for "
            f"decode-style offsets use ring attention or causal=False with "
            f"an explicit mask)")
    if k.shape[2] != v.shape[2]:
        raise ValueError(f"k heads ({k.shape[2]}) != v heads "
                         f"({v.shape[2]})")
    if h != hk:
        if h % hk:
            raise ValueError(f"q heads {h} not a multiple of kv heads {hk}")
        # TODO(gqa): materializes repeated K/V (h/hk× their HBM + bandwidth).
        # The zero-copy alternative maps the kv-head inside the BlockSpec
        # index maps (kv = (bh//h)*hk + (bh%h)//g) and restructures the dkv
        # grid to accumulate over the g group members; revisit if K/V traffic
        # shows up in profiles at 8B scale.
        k = jnp.repeat(k, h // hk, axis=2)
        v = jnp.repeat(v, h // hk, axis=2)
    sk = k.shape[1]
    scale = scale if scale is not None else d ** -0.5
    # [B,S,H,D] → [B·H, S, D]
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    of = _flash(qf, kf, vf, scale, causal, block_q, block_k)
    return of.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
