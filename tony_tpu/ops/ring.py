"""Ring attention: exact attention over a sequence sharded on the ``sp`` axis.

Long-context support is absent from the reference (SURVEY.md §5 "long-context
— absent"); here it is first-class. Each device holds a [B, S/n, H, D] shard
of Q/K/V. K/V chunks rotate around the ``sp`` ring via ``ppermute`` (nearest-
neighbour ICI traffic only) while each device accumulates its Q shard's
online-softmax state — after n steps every Q block has seen every K/V block
and the K/V shards are back home. Compute at step i overlaps the transfer for
step i+1 (XLA schedules the ppermute DMA asynchronously with the compute).

TPU-first structure (the RingAttention-paper blockwise design, built on our
own kernel):

- **Each hop runs the Pallas flash kernel** on (local Q, visiting K/V chunk)
  and yields a normalized partial ``(o, lse)``; hops merge by the exact
  logsumexp rule (``flash_attention_with_lse``). Per-hop memory is
  O(S_local·D) — no [S_local, S_local] score chunk ever exists in HBM, so
  per-device context is bounded by flash's streaming VMEM footprint, not by
  a materialized score matrix.
- **Causally dead hops are skipped, not masked.** Under causal attention the
  visiting chunk is strictly-future for half the hops on average; a
  ``lax.switch`` dispatches diagonal hops to causal flash, past chunks to
  non-causal flash, and future chunks to a free zero/−inf partial (XLA
  conditionals execute one branch — unlike inside a Pallas kernel). The old
  einsum formulation computed every dead chunk and masked it to −inf.
- **GQA is native end-to-end**: K/V rotate at their H_kv width (the per-hop
  ppermute payload — ring attention's bandwidth bottleneck at long context —
  is H/H_kv× smaller than with repeated heads), and the flash BlockSpecs
  index kv-heads directly, so repeated heads never materialize anywhere.

`ring_attention` is the *per-shard* function, for use inside `shard_map`
(this is how model code composes it with other sharded ops);
`ring_attention_sharded` wraps it for global arrays.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tony_tpu.compat import shard_map

from tony_tpu.ops.attention import DEFAULT_BLOCK, flash_attention_with_lse

NEG_INF = -1e30


def bound_axis_size(axis_name: str):
    """Size of a bound mesh axis, None when NO axes are bound (init or
    single-shard trace — callers fall back to local semantics), and a loud
    NameError when other axes ARE bound but this one isn't (a misnamed axis
    under shard_map must not silently degrade to shard-local attention)."""
    try:
        from jax._src import core

        sizes = dict(getattr(core.get_axis_env(), "axis_sizes", {}) or {})
    except Exception:  # private API moved: fall back to probing
        try:
            return jax.lax.psum(1, axis_name)
        except NameError:
            # The requested axis isn't bound — but another mesh axis might
            # be, which would mean a *misnamed* axis, not an unsharded
            # trace. Probe the standard mesh axes so that case still fails
            # loudly instead of silently degrading to shard-local attention.
            from tony_tpu.parallel.mesh import MESH_AXES

            bound = []
            for name in MESH_AXES:
                if name == axis_name:
                    continue
                try:
                    jax.lax.psum(1, name)
                    bound.append(name)
                except NameError:
                    pass
            if bound:
                raise NameError(
                    f"axis {axis_name!r} is not bound under this shard_map; "
                    f"bound axes include: {bound} — pass the right axis_name")
            return None
    if axis_name in sizes:
        return jax.lax.psum(1, axis_name)
    if sizes:
        raise NameError(
            f"axis {axis_name!r} is not bound under this shard_map; bound "
            f"axes: {sorted(sizes)} — pass the right axis_name")
    return None


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str = "sp", causal: bool = True,
                   scale: Optional[float] = None,
                   block_q: int = DEFAULT_BLOCK,
                   block_k: int = DEFAULT_BLOCK) -> jax.Array:
    """Per-shard ring attention ([B, S_local, H, D] in/out; GQA: K/V may
    carry H_kv heads with H_kv | H). Call inside shard_map with the
    sequence dim sharded over ``axis_name``.

    Precision: each hop's partial output leaves the flash kernel as the
    kernel's OWN f32 accumulator (``out_dtype=f32`` — never rounded to the
    input dtype), and hops merge in f32 by the exact logsumexp rule, so
    the only rounding to bf16 is the single final cast. Ring error is
    therefore ~flat in the sp degree (asserted by
    ``test_ring_error_flat_in_sp_degree``); the wire/rotation dtype of the
    K/V chunks stays the input dtype — ICI bandwidth is unchanged."""
    b, s_loc, h, d = q.shape
    hk = k.shape[2]
    if k.shape[2] != v.shape[2]:
        raise ValueError(f"k heads ({k.shape[2]}) != v heads "
                         f"({v.shape[2]})")
    if h % hk:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hk}")
    g = h // hk
    n = bound_axis_size(axis_name)
    if n is None:
        # No axes bound at all (model init / single-shard apply): the
        # "ring" is a single chunk — plain causal attention.
        from tony_tpu.ops.attention import reference_attention
        if g > 1:
            k = jnp.repeat(k, g, axis=2)
            v = jnp.repeat(v, g, axis=2)
        return reference_attention(q, k, v, causal=causal, scale=scale)
    my = jax.lax.axis_index(axis_name)
    scale = scale if scale is not None else d ** -0.5
    perm = [(j, (j + 1) % n) for j in range(n)]
    flash = functools.partial(flash_attention_with_lse, scale=scale,
                              block_q=block_q, block_k=block_k,
                              out_dtype=jnp.float32)

    def hop_full(args):
        k_c, v_c = args
        return flash(q, k_c, v_c, causal=False)

    def hop_diag(args):
        k_c, v_c = args
        return flash(q, k_c, v_c, causal=True)

    def hop_skip(args):
        return (jnp.zeros((b, s_loc, h, d), jnp.float32),
                jnp.full((b, s_loc, h), NEG_INF, jnp.float32))

    def step(carry, i):
        k_c, v_c, lse_acc, o_acc = carry
        # After i forward rotations we hold the chunk originally on (my - i).
        kv_idx = (my - i) % n
        if causal:
            case = jnp.where(kv_idx == my, 2,
                             jnp.where(kv_idx < my, 1, 0))
            o_c, lse_c = jax.lax.switch(
                case, [hop_skip, hop_full, hop_diag], (k_c, v_c))
        else:
            o_c, lse_c = hop_full((k_c, v_c))
        lse_new = jnp.logaddexp(lse_acc, lse_c)
        o_acc = (o_acc * jnp.exp(lse_acc - lse_new)[..., None]
                 + o_c * jnp.exp(lse_c - lse_new)[..., None])
        k_c, v_c = jax.lax.ppermute((k_c, v_c), axis_name, perm)
        return (k_c, v_c, lse_new, o_acc), None

    lse0 = jnp.full((b, s_loc, h), NEG_INF, jnp.float32)
    o0 = jnp.zeros((b, s_loc, h, d), jnp.float32)
    (_, _, _, o_acc), _ = jax.lax.scan(
        step, (k, v, lse0, o0), jnp.arange(n))
    return o_acc.astype(q.dtype)


def ring_attention_sharded(mesh: Mesh, q: jax.Array, k: jax.Array,
                           v: jax.Array, causal: bool = True,
                           scale: Optional[float] = None,
                           axis_name: str = "sp",
                           block_q: int = DEFAULT_BLOCK,
                           block_k: int = DEFAULT_BLOCK) -> jax.Array:
    """Global-array wrapper: [B, S, H, D] with S sharded over ``axis_name``,
    batch over (dp, fsdp), heads replicated along sp."""
    spec = P(("dcn_dp", "dp", "fsdp"), axis_name, None, None)
    fn = functools.partial(ring_attention, axis_name=axis_name,
                           causal=causal, scale=scale,
                           block_q=block_q, block_k=block_k)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)
