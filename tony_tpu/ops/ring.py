"""Ring attention: exact attention over a sequence sharded on the ``sp`` axis.

Long-context support is absent from the reference (SURVEY.md §5 "long-context
— absent"); here it is first-class. Each device holds a [B, S/n, H, D] shard
of Q/K/V. K/V chunks rotate around the ``sp`` ring via ``ppermute`` (nearest-
neighbour ICI traffic only) while each device accumulates its Q shard's
online-softmax state — after n steps every Q block has seen every K/V block
and the K/V shards are back home. Compute at step i overlaps the transfer for
step i+1 (XLA schedules the ppermute DMA asynchronously with the einsums).

`ring_attention` is the *per-shard* function, for use inside `shard_map`
(this is how model code composes it with other sharded ops);
`ring_attention_sharded` wraps it for global arrays.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

NEG_INF = -1e30


def bound_axis_size(axis_name: str):
    """Size of a bound mesh axis, None when NO axes are bound (init or
    single-shard trace — callers fall back to local semantics), and a loud
    NameError when other axes ARE bound but this one isn't (a misnamed axis
    under shard_map must not silently degrade to shard-local attention)."""
    try:
        from jax._src import core

        sizes = dict(getattr(core.get_axis_env(), "axis_sizes", {}) or {})
    except Exception:  # private API moved: fall back to probing
        try:
            return jax.lax.psum(1, axis_name)
        except NameError:
            # The requested axis isn't bound — but another mesh axis might
            # be, which would mean a *misnamed* axis, not an unsharded
            # trace. Probe the standard mesh axes so that case still fails
            # loudly instead of silently degrading to shard-local attention.
            from tony_tpu.parallel.mesh import MESH_AXES

            bound = []
            for name in MESH_AXES:
                if name == axis_name:
                    continue
                try:
                    jax.lax.psum(1, name)
                    bound.append(name)
                except NameError:
                    pass
            if bound:
                raise NameError(
                    f"axis {axis_name!r} is not bound under this shard_map; "
                    f"bound axes include: {bound} — pass the right axis_name")
            return None
    if axis_name in sizes:
        return jax.lax.psum(1, axis_name)
    if sizes:
        raise NameError(
            f"axis {axis_name!r} is not bound under this shard_map; bound "
            f"axes: {sorted(sizes)} — pass the right axis_name")
    return None


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str = "sp", causal: bool = True,
                   scale: Optional[float] = None) -> jax.Array:
    """Per-shard ring attention ([B, S_local, H, D] in/out; GQA: K/V may
    carry H_kv heads with H_kv | H). Call inside shard_map with the
    sequence dim sharded over ``axis_name``.

    GQA is native: K/V rotate around the ring at their H_kv width, so the
    per-hop ppermute payload — ring attention's bandwidth bottleneck at
    long context — is H/H_kv× smaller than with repeated heads."""
    b, s_loc, h, d = q.shape
    hk = k.shape[2]
    if k.shape[2] != v.shape[2]:
        raise ValueError(f"k heads ({k.shape[2]}) != v heads "
                         f"({v.shape[2]})")
    if h % hk:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hk}")
    g = h // hk
    n = bound_axis_size(axis_name)
    if n is None:
        # No axes bound at all (model init / single-shard apply): the
        # "ring" is a single chunk — plain causal attention.
        from tony_tpu.ops.attention import reference_attention
        if g > 1:
            k = jnp.repeat(k, g, axis=2)
            v = jnp.repeat(v, g, axis=2)
        return reference_attention(q, k, v, causal=causal, scale=scale)
    my = jax.lax.axis_index(axis_name)
    scale = scale if scale is not None else d ** -0.5
    perm = [(j, (j + 1) % n) for j in range(n)]

    # [B,S,H,D] → [B,Hk,G,Sq,D]: group axis next to its kv head so the
    # dots batch over (B, Hk) and broadcast over G.
    q_f = q.astype(jnp.float32).transpose(0, 2, 1, 3).reshape(
        b, hk, g, s_loc, d)

    def step(carry, i):
        k_c, v_c, m, l, acc = carry
        # After i forward rotations we hold the chunk originally on (my - i).
        kv_idx = (my - i) % n
        s = jax.lax.dot_general(
            q_f, k_c.astype(jnp.float32).transpose(0, 2, 1, 3),
            (((4,), (3,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32) * scale  # [B,Hk,G,Sq,Sk]
        if causal:
            rows = my * s_loc + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 3)
            cols = kv_idx * s_loc + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 4)
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[..., None] + jax.lax.dot_general(
            p, v_c.astype(jnp.float32).transpose(0, 2, 1, 3),
            (((4,), (2,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32)          # [B,Hk,G,Sq,D]
        k_c, v_c = jax.lax.ppermute((k_c, v_c), axis_name, perm)
        return (k_c, v_c, m_new, l_new, acc_new), None

    m0 = jnp.full((b, hk, g, s_loc), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, hk, g, s_loc), jnp.float32)
    acc0 = jnp.zeros((b, hk, g, s_loc, d), jnp.float32)
    (_, _, _, l, acc), _ = jax.lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(n))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.reshape(b, h, s_loc, d).transpose(0, 2, 1, 3).astype(q.dtype)


def ring_attention_sharded(mesh: Mesh, q: jax.Array, k: jax.Array,
                           v: jax.Array, causal: bool = True,
                           scale: Optional[float] = None,
                           axis_name: str = "sp") -> jax.Array:
    """Global-array wrapper: [B, S, H, D] with S sharded over ``axis_name``,
    batch over (dp, fsdp), heads replicated along sp."""
    spec = P(("dcn_dp", "dp", "fsdp"), axis_name, None, None)
    fn = functools.partial(ring_attention, axis_name=axis_name,
                           causal=causal, scale=scale)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)
