"""Hot-path TPU ops: Pallas kernels + distributed attention patterns.

New work relative to the reference (SURVEY.md §2.3/§5 "long-context —
absent"): TonY never touches a tensor; here the framework owns the flash /
ring / Ulysses attention paths that make long-context training possible on
TPU slices.
"""

from tony_tpu.ops.attention import (  # noqa: F401
    flash_attention, reference_attention,
)
from tony_tpu.ops.ring import (  # noqa: F401
    ring_attention, ring_attention_sharded,
)
from tony_tpu.ops.ulysses import (  # noqa: F401
    ulysses_attention, ulysses_attention_sharded,
)
from tony_tpu.ops.quant import (  # noqa: F401
    QDense, quantized_matmul, quantize_symmetric, resolve_mode,
)
from tony_tpu.ops.convfuse import fused_groupnorm_relu  # noqa: F401
