"""HBM-aware fused GroupNorm→ReLU for the resnet conv trunk.

BENCH_r05 pins the resnet workload at 0.13 MFU with every conv fusion
HBM-bound (~700 GiB/s measured, xprof r5): the chip's 240 FLOPs/byte
ratio, not the MXU, is the ceiling, so the lever is *fewer HBM passes
per conv→norm→relu chain*, not faster matmuls. ``nn.GroupNorm`` + a
separate ``nn.relu`` walks the [B, H, W, C] activation several times
(stats, normalize, affine, relu) and saves the normalized tensor for
backward. This module collapses the chain:

- **One-pass stats.** mean and E[x²] per (batch, group) come from a
  single fused reduction sweep (XLA fuses the two reductions over the
  same operand into one pass).
- **Folded affine.** scale/rsqrt/mean/bias collapse into per-(B, C)
  ``a``/``b`` vectors, so normalize+affine+relu is ONE fused
  multiply-add-max over the activation — a Pallas kernel on TPU (one
  HBM read + one write, ``pallas_guide.md``), a single fused ``lax``
  expression everywhere else (the portable path tier-1 CPU runs).
- **Remat'd epilogue.** The fused apply sits under ``jax.checkpoint``
  (on by default): backward recomputes the cheap normalize instead of
  keeping the [B, H, W, C] normalized tensor resident — HBM footprint
  and write traffic both drop.

Degrade discipline matches ops/quant.py: the Pallas path is probed once
per backend with a tiny eager call; any refusal falls back to the lax
composition with a one-time warning — the fused trunk may lose its
kernel, never the job. models/resnet.py threads this through every
bottleneck via ``ResNetConfig.fused`` (on by default; the unfused
GroupNorm path stays as the parity twin).
"""

from __future__ import annotations

import functools
import logging
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

log = logging.getLogger(__name__)

#: row-block for the Pallas apply kernel ([rows, C] tiles of the
#: flattened [B, H·W, C] view).
APPLY_BLOCK_ROWS = 256

_pallas_fallback_reason: Optional[str] = None


def group_stats(x: jax.Array, groups: int):
    """(mean, var) per (batch, group) over spatial dims and the group's
    channels, f32, one fused sweep (E[x²] − E[x]² with a non-negative
    clamp)."""
    b, c = x.shape[0], x.shape[-1]
    xg = x.reshape(b, -1, groups, c // groups).astype(jnp.float32)
    mean = jnp.mean(xg, axis=(1, 3))
    ex2 = jnp.mean(jnp.square(xg), axis=(1, 3))
    var = jnp.maximum(ex2 - jnp.square(mean), 0.0)
    return mean, var


def folded_affine(mean: jax.Array, var: jax.Array, scale: jax.Array,
                  bias: jax.Array, channels: int, eps: float):
    """Fold (mean, var, scale, bias) into per-(B, C) ``a``/``b`` so the
    whole normalize+affine is ``x * a + b`` — one fused elementwise pass
    instead of GroupNorm's subtract/rsqrt/mul/mul/add chain."""
    groups = mean.shape[-1]
    inv = lax.rsqrt(var + eps)                          # [B, G]
    cg = channels // groups
    inv_c = jnp.repeat(inv, cg, axis=1)                 # [B, C]
    mean_c = jnp.repeat(mean, cg, axis=1)
    a = inv_c * scale.astype(jnp.float32)[None, :]
    b = bias.astype(jnp.float32)[None, :] - mean_c * a
    return a, b


def _apply_lax(x: jax.Array, a: jax.Array, b: jax.Array,
               relu: bool) -> jax.Array:
    """Portable fused apply: one multiply-add(-max) expression XLA fuses
    into a single pass (and into the neighbouring conv where it can)."""
    shape = (x.shape[0],) + (1,) * (x.ndim - 2) + (x.shape[-1],)
    y = x.astype(jnp.float32) * a.reshape(shape) + b.reshape(shape)
    if relu:
        y = jnp.maximum(y, 0.0)
    return y.astype(x.dtype)


def _apply_kernel(x_ref, a_ref, b_ref, o_ref, *, relu):
    y = x_ref[0].astype(jnp.float32) * a_ref[0] + b_ref[0]
    if relu:
        y = jnp.maximum(y, 0.0)
    o_ref[0] = y.astype(o_ref.dtype)


def _apply_pallas(x: jax.Array, a: jax.Array, b: jax.Array, relu: bool,
                  interpret: bool) -> jax.Array:
    """One-HBM-pass apply: grid over (batch, row blocks) of the
    flattened [B, H·W, C] view; a/b ride along as [1, C] blocks."""
    batch, c = x.shape[0], x.shape[-1]
    x2 = x.reshape(batch, -1, c)
    rows = x2.shape[1]
    block = min(APPLY_BLOCK_ROWS, rows)
    grid = (batch, pl.cdiv(rows, block))
    out = pl.pallas_call(
        functools.partial(_apply_kernel, relu=relu),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block, c), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, c), lambda i, j: (i, 0)),
            pl.BlockSpec((1, c), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block, c), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct(x2.shape, x.dtype),
        interpret=interpret,
    )(x2, a, b)
    return out.reshape(x.shape)


@functools.lru_cache(maxsize=None)
def _pallas_ok(backend: str) -> bool:
    """Probe the Pallas apply once per backend (tiny eager call, CPU
    interpret mode included); any refusal degrades to the lax path with
    a one-time warning."""
    global _pallas_fallback_reason
    try:
        x = jnp.ones((1, 8, 8), jnp.float32)
        ab = jnp.ones((1, 8), jnp.float32)
        out = _apply_pallas(x, ab, ab, True, backend != "tpu")
        jax.block_until_ready(out)
    except Exception as e:  # noqa: BLE001 — any refusal shape degrades
        _pallas_fallback_reason = f"{type(e).__name__}: {e}"[:200]
        log.warning(
            "fused groupnorm Pallas apply unavailable on backend %r "
            "(%s); DEGRADING to the fused lax composition (one-time "
            "warning)", backend, _pallas_fallback_reason)
        return False
    return True


def fused_groupnorm_relu(x: jax.Array, scale: jax.Array, bias: jax.Array,
                         *, groups: int, eps: float = 1e-6,
                         relu: bool = True,
                         use_pallas: Optional[bool] = None,
                         remat: bool = True) -> jax.Array:
    """GroupNorm (+ optional ReLU) in two HBM passes: one fused stats
    sweep, one fused folded-affine apply. Numerically matches
    ``nn.relu(nn.GroupNorm(num_groups=groups)(x))`` to f32 tolerance.

    ``use_pallas=None`` auto-selects: the Pallas kernel on TPU (probed
    once, degrades to lax), interpret-mode Pallas only when forced
    (unit tests), the lax composition otherwise. ``remat=True`` wraps
    the apply in ``jax.checkpoint`` so backward recomputes it instead of
    keeping the normalized activation resident."""
    c = x.shape[-1]
    if c % groups:
        raise ValueError(f"channels {c} not divisible by groups {groups}")
    mean, var = group_stats(x, groups)
    a, b = folded_affine(mean, var, scale, bias, c, eps)

    backend = jax.default_backend()
    if use_pallas is None:
        use_pallas = backend == "tpu" and _pallas_ok(backend)
    elif use_pallas:
        use_pallas = _pallas_ok(backend)

    if use_pallas:
        def apply(x, a, b):
            return _apply_pallas(x, a, b, relu, backend != "tpu")
    else:
        def apply(x, a, b):
            return _apply_lax(x, a, b, relu)

    if remat:
        apply = jax.checkpoint(apply)
    return apply(x, a, b)
