"""Ulysses-style sequence parallelism: all-to-all head/sequence swap.

The other long-context pattern (SURVEY.md §5): instead of rotating K/V chunks
(ring), transpose the sharding — two ``all_to_all`` collectives swap a
sequence-sharded layout [B, S/n, H, D] into a head-sharded layout
[B, S, H/n, D], run *full-sequence* attention locally on each device's head
group (using the Pallas flash kernel), then swap back. Communication is two
all-to-alls regardless of sequence length, which beats the ring when heads
divide evenly and the per-device full sequence fits HBM.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
from jax.sharding import Mesh, PartitionSpec as P

from tony_tpu.compat import shard_map
from tony_tpu.ops.attention import DEFAULT_BLOCK, flash_attention


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str = "sp", causal: bool = True,
                      scale: Optional[float] = None,
                      block_q: int = DEFAULT_BLOCK,
                      block_k: int = DEFAULT_BLOCK) -> jax.Array:
    """Per-shard Ulysses attention ([B, S_local, H, D] in/out), for use
    inside shard_map. Requires both q and k/v head counts divisible by the
    axis size."""

    from tony_tpu.ops.ring import bound_axis_size

    if bound_axis_size(axis_name) is None:
        # No axes bound at all (model init / single-shard apply): no swap.
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               block_q=block_q, block_k=block_k)

    def seq_to_heads(x):
        # [B, S/n, H, D] → [B, S, H/n, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    oh = flash_attention(qh, kh, vh, causal=causal, scale=scale,
                         block_q=block_q, block_k=block_k)
    return heads_to_seq(oh)


def ulysses_attention_sharded(mesh: Mesh, q: jax.Array, k: jax.Array,
                              v: jax.Array, causal: bool = True,
                              scale: Optional[float] = None,
                              axis_name: str = "sp",
                              block_q: int = DEFAULT_BLOCK,
                              block_k: int = DEFAULT_BLOCK) -> jax.Array:
    """Global-array wrapper: [B, S, H, D] with S sharded over ``axis_name``."""
    n = mesh.shape[axis_name]
    if q.shape[2] % n or k.shape[2] % n:
        raise ValueError(f"Ulysses needs q heads ({q.shape[2]}) and kv "
                         f"heads ({k.shape[2]}) divisible by the "
                         f"{axis_name!r} axis size ({n}); use ring "
                         f"attention instead")
    spec = P(("dcn_dp", "dp", "fsdp"), axis_name, None, None)
    fn = functools.partial(ulysses_attention, axis_name=axis_name,
                           causal=causal, scale=scale,
                           block_q=block_q, block_k=block_k)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)
