"""Low-precision (int8 / fp8-e4m3) matmul paths for the training hot loop.

The Gemma-on-TPU comparison (PAPERS.md) attributes most of its TPU win to
low-precision matmuls: v5e's MXU runs int8 at 2x the bf16 rate (394 vs
197 TOPS), and the flagship's attention/MLP projections are plain
``x @ W`` contractions that tolerate symmetric per-channel quantization.
This module is that lever, opt-in via ``tony.train.matmul-dtype``
(`TransformerConfig.matmul_dtype` threads it into every ``_dense``
projection in models/transformer.py):

- **Symmetric, per-channel, round-to-nearest.** Activations get one scale
  per row (amax over the contraction dim), weights one per output
  channel; no zero points, no stochastic rounding — dequantization is two
  rank-1 scale multiplies on the f32/int32 accumulator.
- **Forward-only.** The quantized dot runs under a ``jax.custom_vjp``
  whose backward is the exact full-precision matmul gradient
  (straight-through estimator): training dynamics stay within the
  loss-parity tolerance of the bf16 golden (test-gated over the bench
  window), and disabling the knob restores the *bitwise* bf16 path
  (``QDense`` with the knob unset replicates ``nn.Dense`` exactly).
- **Degrade, never die.** ``resolve_mode`` probes the backend once per
  (mode, backend) with a tiny eager dot; an unsupported backend (or the
  ``quant.probe`` fault site) downgrades the path to bf16 with a
  ONE-TIME warning that also rides the telemetry metrics beacon
  (``quant_fallback``) — a refused quantized path must cost throughput,
  not the job.

When quantization is unsafe (loss-scale-sensitive runs, custom loss
scaling, <1e-2 gradient magnitudes): see docs/operations.md "Spending
the verdict".
"""

from __future__ import annotations

import functools
import logging
import threading
from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

log = logging.getLogger(__name__)

INT8 = "int8"
FP8_E4M3 = "fp8_e4m3"
#: the modes resolve_mode accepts (anything else raises).
MODES = (INT8, FP8_E4M3)
#: spellings that mean "quantization off".
_OFF = (None, "", "bf16", "none", "off")

_INT8_MAX = 127.0
_FP8_E4M3_MAX = 448.0       # largest finite float8_e4m3fn
_EPS = 1e-12

_fallback_lock = threading.Lock()
_fallbacks: Dict[str, str] = {}


def fallback_events() -> Dict[str, str]:
    """{mode: reason} for every quantized path that degraded to bf16 in
    this process — shipped on the telemetry metrics beacon so the
    one-time event is visible in `top`/metrics, not just a log line."""
    with _fallback_lock:
        return dict(_fallbacks)


def _record_fallback(mode: str, reason: str) -> None:
    with _fallback_lock:
        if mode in _fallbacks:
            return
        _fallbacks[mode] = reason
    log.warning(
        "quantized matmul path %r unavailable on this backend (%s); "
        "DEGRADING to the bf16 path — throughput loses the low-precision "
        "win, the job keeps training (one-time warning)", mode, reason)


@functools.lru_cache(maxsize=None)
def _probe(mode: str, backend: str) -> str:
    """Empty string when the backend runs the quantized dot; else the
    refusal reason. Cached per (mode, backend) — the probe is a tiny
    eager computation, run once."""
    from tony_tpu import faults

    try:
        faults.check("quant.probe")
        if mode == INT8:
            a = jnp.ones((8, 8), jnp.int8)
            out = lax.dot_general(a, a, (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.int32)
        else:
            f8 = jnp.ones((8, 8), jnp.float8_e4m3fn)
            out = lax.dot_general(f8, f8, (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        jax.block_until_ready(out)
    except Exception as e:  # noqa: BLE001 — any refusal shape degrades
        return f"{type(e).__name__}: {e}"[:200]
    return ""


def resolve_mode(mode: Optional[str]) -> Optional[str]:
    """Effective quantization mode: None when off or degraded (use the
    bf16 path), else the validated mode. Unknown names raise — a typo'd
    knob must fail loudly at trace time, not silently train in bf16."""
    if mode in _OFF:
        return None
    if mode not in MODES:
        raise ValueError(
            f"unknown tony.train.matmul-dtype {mode!r} (choose from "
            f"{list(MODES)}, or empty for bf16)")
    reason = _probe(mode, jax.default_backend())
    if reason:
        _record_fallback(mode, reason)
        return None
    return mode


def _reset_fallback_state() -> None:
    """Tests: forget recorded fallbacks and probe results."""
    with _fallback_lock:
        _fallbacks.clear()
    _probe.cache_clear()


def quantize_symmetric(x: jax.Array, mode: str, axis: int):
    """Per-channel symmetric quantization along ``axis`` (the contraction
    dim): returns ``(q, scale)`` with ``q * scale ~= x`` and ``scale``
    keeping dims (f32). int8 rounds to nearest; fp8 relies on the cast's
    rounding. Scales come from the f32 amax so bf16 inputs don't lose
    their own range computation."""
    qmax = _INT8_MAX if mode == INT8 else _FP8_E4M3_MAX
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis,
                   keepdims=True)
    scale = jnp.maximum(amax, _EPS) / qmax
    y = x.astype(jnp.float32) / scale
    if mode == INT8:
        q = jnp.clip(jnp.round(y), -_INT8_MAX, _INT8_MAX).astype(jnp.int8)
    else:
        q = jnp.clip(y, -_FP8_E4M3_MAX, _FP8_E4M3_MAX).astype(
            jnp.float8_e4m3fn)
    return q, scale


def _qmm_forward(x: jax.Array, w: jax.Array, mode: str) -> jax.Array:
    """The quantized contraction: x [..., K] @ w [K, N] with per-row /
    per-output-channel scales; accumulate int32 (int8) or f32 (fp8)."""
    qx, sx = quantize_symmetric(x, mode, axis=-1)       # sx [..., 1]
    qw, sw = quantize_symmetric(w, mode, axis=0)        # sw [1, N]
    dims = (((x.ndim - 1,), (0,)), ((), ()))
    if mode == INT8:
        acc = lax.dot_general(qx, qw, dims,
                              preferred_element_type=jnp.int32)
        acc = acc.astype(jnp.float32)
    else:
        acc = lax.dot_general(qx, qw, dims,
                              preferred_element_type=jnp.float32)
    out = acc * sx * sw
    return out.astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def quantized_matmul(x: jax.Array, w: jax.Array, mode: str) -> jax.Array:
    """``x @ w`` through the quantized path; gradients are the exact
    full-precision matmul gradients (straight-through) so backward
    numerics are untouched by quantization noise."""
    return _qmm_forward(x, w, mode)


def _qmm_fwd(x, w, mode):
    return _qmm_forward(x, w, mode), (x, w)


def _qmm_bwd(mode, res, g):
    x, w = res
    g = g.astype(x.dtype)
    dims_dx = (((g.ndim - 1,), (1,)), ((), ()))
    dx = lax.dot_general(g, w, dims_dx)                 # g @ w.T
    x2 = x.reshape(-1, x.shape[-1])
    g2 = g.reshape(-1, g.shape[-1])
    dw = lax.dot_general(x2, g2, (((0,), (0,)), ((), ())))  # x.T @ g
    return dx.astype(x.dtype), dw.astype(w.dtype)


quantized_matmul.defvjp(_qmm_fwd, _qmm_bwd)


class QDense(nn.Module):
    """``nn.Dense(use_bias=False)`` with an opt-in quantized forward.

    With ``matmul_dtype`` unset (or resolved to a fallback) this module
    replicates ``nn.Dense``'s exact math — same param name/init/path,
    same ``promote_dtype``, same ``lax.dot_general`` call — so switching
    the knob off restores bitwise-identical behaviour, and an
    unsupported backend degrades to numbers indistinguishable from the
    unquantized model."""

    features: int
    dtype: Any = None
    param_dtype: Any = jnp.float32
    kernel_init: Any = nn.initializers.lecun_normal()
    matmul_dtype: str = ""

    @nn.compact
    def __call__(self, x):
        kernel = self.param("kernel", self.kernel_init,
                            (jnp.shape(x)[-1], self.features),
                            self.param_dtype)
        x, kernel = nn.dtypes.promote_dtype(x, kernel, dtype=self.dtype)
        mode = resolve_mode(self.matmul_dtype)
        if mode is None:
            # The nn.Dense path, verbatim (use_bias=False, precision
            # default) — the bitwise-identity contract.
            return lax.dot_general(
                x, kernel, (((x.ndim - 1,), (0,)), ((), ())),
                precision=None)
        return quantized_matmul(x, kernel, mode)
