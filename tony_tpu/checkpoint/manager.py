"""Async sharded checkpointing for train state.

The reference delegates checkpointing entirely to user code (SURVEY.md §5:
"TonY provides no checkpoint manager; resume-after-AM-retry works only
because user scripts re-read checkpoints from HDFS" — e.g.
``MonitoredTrainingSession(checkpoint_dir=...)`` in
``tony-examples/mnist-tensorflow``). A TPU framework cannot: multi-host
sharded state needs coordinated, topology-aware save/restore. This wraps
orbax — async so the save overlaps the next training steps, sharding-aware
so each host writes only its own shards and restore re-lays-out onto any
mesh with matching global shapes.

Resume contract with the coordinator's whole-job retry (sessionId epochs,
``ApplicationMaster.java:356-371``): user scripts call ``latest_step()`` at
startup and restore if non-None — a retried session transparently continues
from the last completed save.

Integrity contract (new): every durable step gets a per-file sha256
manifest (``tony-manifest.json`` inside the step directory), written once
the step's async save is finished and verified before any restore. A
restart after preemption/crash trusts NOTHING about the newest step: if
it is partial (killed mid-write) or corrupt (bit rot, truncated upload),
``restore(None, like)`` falls back to the newest step whose manifest
verifies, instead of feeding garbage into 8B parameters and training on.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from tony_tpu import faults, telemetry

log = logging.getLogger(__name__)

MANIFEST_NAME = "tony-manifest.json"


def _hash_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1024 * 1024), b""):
            h.update(chunk)
    return h.hexdigest()


class CheckpointManager:
    """Thin policy wrapper over ``orbax.checkpoint.CheckpointManager``."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 save_interval_steps: int = 1, async_save: bool = True):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self._busy = False               # main thread inside an orbax call
        self._preempt: Optional[dict] = None
        # Orbax wants an absolute path; URLs (gs://...) pass through as-is.
        # (ocp.path.utils.to_absolute_path came and went across releases —
        # resolve locally instead of chasing it.)
        directory = str(directory)
        if "://" not in directory:
            directory = os.path.abspath(directory)
        self._directory = directory
        # Steps saved but not yet checksummed: manifests are written only
        # once the (async) save is durable — wait()/close()/restore().
        self._pending_manifest: set = set()
        # step → {axis: size} mesh shape noted at save time; lands in the
        # step's manifest so restore can tell "same layout" from
        # "reshard" (elastic resize: restore onto a different mesh).
        self._mesh_note: Dict[int, Dict[str, int]] = {}
        # (saved_shape, current_shape) of the last restore that crossed
        # mesh shapes; None when the layouts matched (or were unknown).
        self.last_restore_resharded: Optional[tuple] = None
        # Overlapped-save mode (async_save=True): ``save()`` only pays the
        # device→host snapshot, then hands serialization+fsync+manifest to
        # a background writer thread; the inner orbax manager runs
        # SYNCHRONOUSLY inside that thread so "save returned" == "bytes
        # durable" and the manifest can be committed last (crash
        # consistency: a step without a manifest was torn in flight and
        # the integrity path quarantines it).
        self._overlap = bool(async_save)
        self._save_interval = max(1, int(save_interval_steps))
        self._wcond = threading.Condition()
        self._wqueue: Optional[Tuple[int, Any, bool]] = None  # newest wins
        self._winflight: Optional[int] = None
        self._wstop = False
        self._wthread: Optional[threading.Thread] = None
        self._last_queued: Optional[int] = None
        #: failed background writes ("step N: why") — the step was NOT
        #: committed; restore falls back to the last committed manifest.
        self.async_errors: List[str] = []
        #: queued-but-not-started saves replaced by a newer request
        self.coalesced_saves = 0
        self._mgr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=False,
            ))

    @staticmethod
    def _mesh_shape(mesh: Any) -> Optional[Dict[str, int]]:
        """{axis: size} of a jax Mesh (or an already-shaped mapping)."""
        if mesh is None:
            return None
        shape = getattr(mesh, "shape", mesh)
        try:
            return {str(k): int(v) for k, v in dict(shape).items()}
        except (TypeError, ValueError):
            return None

    def save(self, step: int, state: Any, force: bool = False,
             mesh: Any = None) -> bool:
        """Queue an (async) save; returns False when skipped by the
        save_interval_steps policy. In overlapped mode (async_save=True)
        the training thread pays ONLY the device→host snapshot — the
        serialization, fsync and manifest run on a background writer, so
        a save never stalls a step; ``wait()`` is the durability barrier.
        Every committed step gets an integrity manifest, written strictly
        AFTER its bytes are durable (manifest-last = the commit point).
        ``mesh`` (optional) notes the device-mesh shape in the manifest
        so a restore onto a DIFFERENT mesh — the elastic shrink/grow
        path — is detected and logged as a reshard."""
        faults.check("checkpoint.save")
        step = int(step)
        if self._overlap:
            if not force and not self._policy_should_save(step):
                return False
            self._busy = True
            try:
                # Step-time attribution: the snapshot copy is the ONLY
                # stall the training thread pays in overlapped mode.
                with telemetry.phase("ckpt_stall"):
                    snap = self._host_snapshot(state)
            finally:
                self._busy = False
                self._run_deferred_preemption()
            shape = self._mesh_shape(mesh)
            if shape:
                self._mesh_note[step] = shape
            self._enqueue(step, snap, force)
            return True
        self._busy = True
        try:
            # Step-time attribution rides for free: whatever the (async)
            # save enqueue blocks the training thread for IS the step's
            # checkpoint stall — telemetry's ckpt_stall phase.
            with telemetry.phase("ckpt_stall"):
                saved = self._mgr.save(
                    step, args=self._ocp.args.StandardSave(state),
                    force=force)
        finally:
            self._busy = False
            self._run_deferred_preemption()
        if saved:
            self._pending_manifest.add(step)
            shape = self._mesh_shape(mesh)
            if shape:
                self._mesh_note[step] = shape
        return saved

    # -- overlapped background writer -----------------------------------
    def _policy_should_save(self, step: int) -> bool:
        """save_interval_steps policy for the overlapped path, applied on
        the training thread (the writer always force-saves: the decision
        was already made here). Queued-but-unwritten steps count as saved
        so back-to-back saves coalesce instead of double-writing."""
        latest = self._last_queued
        if latest is None:
            latest = self._mgr.latest_step()
        if latest is None:
            return True
        if step <= latest:
            return False
        return (step - latest) >= self._save_interval \
            or step % self._save_interval == 0

    @staticmethod
    def _host_snapshot(state: Any) -> Any:
        """Copy device arrays to host memory so the background writer
        serializes a frozen snapshot while training mutates the live
        state. Non-addressable (multi-host) leaves stay as device arrays
        — orbax gathers per-host shards itself."""
        import jax
        import numpy as np

        def to_host(x):
            if isinstance(x, jax.Array) and x.is_fully_addressable:
                return np.asarray(x)
            return x

        return jax.tree.map(to_host, state)

    def _enqueue(self, step: int, snap: Any, force: bool) -> None:
        with self._wcond:
            if self._wthread is None:
                self._wthread = threading.Thread(
                    target=self._writer_loop, name="ckpt-async-writer",
                    daemon=True)
                self._wthread.start()
            if self._wqueue is not None:
                # Newest wins: an unstarted queued save is superseded —
                # the writer never falls behind a fast save cadence.
                self.coalesced_saves += 1
                log.info("coalescing queued checkpoint step %d under "
                         "newer step %d", self._wqueue[0], step)
            self._wqueue = (step, snap, force)
            self._last_queued = step
            self._wcond.notify_all()

    def _writer_loop(self) -> None:
        while True:
            with self._wcond:
                while self._wqueue is None and not self._wstop:
                    self._wcond.wait()
                if self._wqueue is None:
                    return
                req = self._wqueue
                self._wqueue = None
                self._winflight = req[0]
            try:
                self._write_one(*req)
            finally:
                with self._wcond:
                    self._winflight = None
                    self._wcond.notify_all()

    def _write_one(self, step: int, snap: Any, force: bool) -> None:
        """One background save: serialize+fsync, then manifest LAST. Any
        failure leaves the step uncommitted (no manifest) — restore falls
        back to the previous committed step; an async write failure must
        never crash training."""
        try:
            faults.check("ckpt.async-write")
            self._mgr.save(step, args=self._ocp.args.StandardSave(snap),
                           force=True)
            self._mgr.wait_until_finished()
            if self._integrity_enabled():
                self._write_manifest(step)
        except Exception as e:  # noqa: BLE001 — degrade, never crash
            log.warning(
                "async checkpoint write of step %d FAILED (%s); step NOT "
                "committed — restore falls back to the last committed "
                "manifest", step, e)
            self.async_errors.append(f"step {step}: {e}")

    def _drain_writer(self) -> None:
        """Block until the writer queue is empty and no write is in
        flight (the durability barrier of overlapped mode)."""
        if self._wthread is None:
            return
        with self._wcond:
            while self._wqueue is not None or self._winflight is not None:
                self._wcond.wait()

    # -- integrity ------------------------------------------------------
    def _step_dir(self, step: int) -> str:
        return os.path.join(self._directory, str(step))

    def _integrity_enabled(self) -> bool:
        # Remote (gs://...) checkpoint dirs go through tensorstore; the
        # local-walk manifest does not apply there.
        return "://" not in self._directory

    def _step_files(self, step: int) -> List[str]:
        """Step-relative paths of every file of a step (manifest excluded)."""
        root = self._step_dir(step)
        out: List[str] = []
        for base, _, files in os.walk(root):
            for f in files:
                if f == MANIFEST_NAME and base == root:
                    continue
                rel = os.path.relpath(os.path.join(base, f), root)
                out.append(rel.replace(os.sep, "/"))
        return sorted(out)

    def _write_manifest(self, step: int) -> None:
        root = self._step_dir(step)
        if not os.path.isdir(root):
            return
        files: Dict[str, Dict[str, Any]] = {}
        for rel in self._step_files(step):
            p = os.path.join(root, rel.replace("/", os.sep))
            files[rel] = {"sha256": _hash_file(p),
                          "size": os.path.getsize(p)}
        doc: Dict[str, Any] = {"step": int(step), "files": files}
        if step in self._mesh_note:
            doc["mesh"] = self._mesh_note[step]
        # The manifest is the verified-restore contract: it must never be
        # adoptable half-written, and it must survive the host crash that
        # the restore is for — full atomic_write discipline.
        from tony_tpu.utils.durable import atomic_write

        atomic_write(os.path.join(root, MANIFEST_NAME),
                     json.dumps(doc, sort_keys=True).encode("utf-8"))

    def _flush_manifests(self) -> None:
        """Write manifests for every step whose save is now durable.
        ONLY call with no async save in flight (after
        wait_until_finished)."""
        if not self._integrity_enabled():
            self._pending_manifest.clear()
            return
        for step in sorted(self._pending_manifest):
            try:
                self._write_manifest(step)
            except OSError as e:
                # A garbage-collected step (max_to_keep) has no dir left.
                log.debug("no manifest for step %d: %s", step, e)
        self._pending_manifest.clear()

    def manifest_path(self, step: int) -> str:
        return os.path.join(self._step_dir(step), MANIFEST_NAME)

    def verify_step(self, step: int) -> bool:
        """True iff the step has a manifest and every listed file exists
        with matching size+sha256 (extra files are tolerated — later orbax
        versions may add metadata)."""
        mpath = self.manifest_path(step)
        try:
            with open(mpath, encoding="utf-8") as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            return False
        root = self._step_dir(step)
        for rel, meta in (manifest.get("files") or {}).items():
            p = os.path.join(root, rel.replace("/", os.sep))
            try:
                if os.path.getsize(p) != meta.get("size"):
                    log.warning("checkpoint step %d: %s size mismatch",
                                step, rel)
                    return False
                if _hash_file(p) != meta.get("sha256"):
                    log.warning("checkpoint step %d: %s checksum mismatch",
                                step, rel)
                    return False
            except OSError:
                log.warning("checkpoint step %d: %s missing/unreadable",
                            step, rel)
                return False
        return True

    def latest_verified_step(self) -> Optional[int]:
        """Newest step whose manifest verifies (None when none do)."""
        self.wait()
        for step in sorted(self._mgr.all_steps(), reverse=True):
            if self.verify_step(int(step)):
                return int(step)
        return None

    def saved_mesh_shape(self, step: int) -> Optional[Dict[str, int]]:
        """The {axis: size} mesh shape noted in a step's manifest at save
        time (None: no manifest, or saved by a build/caller that noted
        none)."""
        try:
            with open(self.manifest_path(step), encoding="utf-8") as f:
                shape = json.load(f).get("mesh")
        except (OSError, ValueError):
            return None
        if not isinstance(shape, dict):
            return None
        try:
            return {str(k): int(v) for k, v in shape.items()}
        except (TypeError, ValueError):
            return None

    def _note_reshard(self, step: int, mesh: Any) -> None:
        """Record whether this restore crossed mesh shapes (elastic
        resize: a manifest saved at (dp=2,tp=4) restored onto
        (dp=2,tp=3)). The re-layout itself is orbax's StandardRestore
        honouring the target shardings — this is the observable."""
        self.last_restore_resharded = None
        current = self._mesh_shape(mesh)
        if current is None:
            return
        saved = self.saved_mesh_shape(step)
        if saved is None:
            return
        if saved != current:
            self.last_restore_resharded = (saved, current)
            log.warning(
                "checkpoint step %d: resharding on restore — saved at "
                "mesh %s, restoring onto %s (elastic re-mesh)", step,
                saved, current)

    def restore(self, step: Optional[int], like: Any,
                verify: bool = True, mesh: Any = None) -> Any:
        """Restore ``step`` (or the newest GOOD step when None) with the
        shardings of ``like`` — pass the freshly-initialized state (or an
        eval_shape of it with NamedSharding leaves) so every shard lands
        on its device. ``mesh`` (optional, the CURRENT mesh) is compared
        against the shape noted in the step's manifest: a mismatch is
        the elastic reshard-on-restore path, logged and recorded in
        ``last_restore_resharded``.

        With ``step=None`` and ``verify`` (the default), candidates are
        tried newest-first: a step whose manifest verifies is restored; a
        step whose manifest FAILS verification (truncated/corrupt files)
        is skipped with a warning; a step with no manifest at all (saved
        by an older build, or the process died before the manifest flush)
        is attempted and skipped only if orbax itself rejects it. An
        explicit ``step`` is restored as requested — failing loudly if
        its manifest does not verify."""
        import jax

        target = jax.tree.map(
            lambda x: (jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
                       if hasattr(x, "sharding") else x), like)
        verify = verify and self._integrity_enabled()
        if step is not None:
            step = int(step)
            self._drain_writer()   # an in-flight write of THIS step
            if verify and os.path.exists(self.manifest_path(step)) \
                    and not self.verify_step(step):
                raise IOError(
                    f"checkpoint step {step} failed integrity "
                    f"verification ({self.manifest_path(step)})")
            self._note_reshard(step, mesh)
            return self._mgr.restore(
                step, args=self._ocp.args.StandardRestore(target))
        self.wait()          # flushes pending manifests too
        candidates = sorted(self._mgr.all_steps(), reverse=True)
        if not candidates:
            raise FileNotFoundError("no checkpoint to restore")
        errors: List[str] = []
        for cand in candidates:
            cand = int(cand)
            has_manifest = os.path.exists(self.manifest_path(cand))
            if verify and has_manifest and not self.verify_step(cand):
                log.warning(
                    "checkpoint step %d is PARTIAL/CORRUPT — falling back "
                    "to the previous verified step", cand)
                errors.append(f"step {cand}: integrity check failed")
                # Quarantine: a rejected step is garbage that would keep
                # shadowing latest_step() AND block the resumed run from
                # re-saving the same step number (orbax refuses to
                # overwrite an existing step).
                try:
                    self._mgr.delete(cand)
                    log.warning("deleted corrupt checkpoint step %d", cand)
                except Exception as e:  # noqa: BLE001 — best-effort
                    log.warning("could not delete corrupt step %d: %s",
                                cand, e)
                continue
            try:
                self._note_reshard(cand, mesh)
                out = self._mgr.restore(
                    cand, args=self._ocp.args.StandardRestore(target))
                if cand != candidates[0]:
                    log.warning("restored verified step %d (newest was %d)",
                                cand, int(candidates[0]))
                return out
            except Exception as e:  # noqa: BLE001 — try the next-older step
                if not verify:
                    raise
                log.warning("restore of step %d failed (%s); trying older",
                            cand, e)
                errors.append(f"step {cand}: {e}")
        raise FileNotFoundError(
            "no restorable checkpoint: " + "; ".join(errors))

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def install_preemption_handler(self, snapshot, exit_code: int = 143
                                   ) -> None:
        """Save-on-SIGTERM: when the job is being torn down (force-kill,
        epoch reset, slice teardown), synchronously save the state
        ``snapshot()`` returns, then exit.

        This is the consumer of the kill chain's TERM→grace→KILL contract
        (executor forwards SIGTERM to the user process group and backends
        honour a grace window — utils/proc.py, cluster/*): the handler
        gets the grace to make one final durable save, so a resumed job
        loses zero completed steps instead of rolling back to the last
        periodic save. ``snapshot`` must return ``(step, state)`` and be
        cheap to call from the main thread (it runs between Python
        bytecodes — a jitted step in flight completes first).

        Install from the MAIN thread of the training process. Exits with
        ``exit_code`` (default 143 = 128+SIGTERM, what the supervisor
        expects of a TERM'd task).
        """
        import signal

        self._preempt = {"fired": False, "deferred": False,
                         "snapshot": snapshot, "exit_code": exit_code}

        def _handler(signum, frame):
            st = self._preempt
            if st["fired"]:
                # Teardown delivers TERM more than once (the executor
                # forwards it AND the backend signals the user group
                # directly) — first one wins, the rest no-op.
                return
            if self._busy:
                # TERM landed while the main thread is INSIDE an orbax
                # call (a periodic save/wait): a re-entrant save would
                # corrupt the in-flight write ("Executor shutdown has
                # been called"). Defer — save()/wait() run the final
                # save the moment the in-flight call completes.
                st["deferred"] = True
                return
            st["fired"] = True
            self._do_preemption_save()

        signal.signal(signal.SIGTERM, _handler)

    def _run_deferred_preemption(self) -> None:
        st = self._preempt
        if st is not None and st["deferred"] and not st["fired"]:
            st["fired"] = True
            self._do_preemption_save()

    def _do_preemption_save(self) -> None:
        import sys

        st = self._preempt
        try:
            step, state = st["snapshot"]()
            log.warning("SIGTERM: saving preemption checkpoint at step %s",
                        step)
            self.save(int(step), state, force=True)
            self.wait()
            log.warning("preemption checkpoint durable; exiting")
        except Exception:  # noqa: BLE001 — still exit promptly
            log.exception("preemption save failed")
        sys.exit(st["exit_code"])

    def wait(self) -> None:
        """Block until queued async saves are durable (call before exit);
        durable steps then get their integrity manifest."""
        self._busy = True
        try:
            # A mid-training wait() is exactly the stall async
            # checkpointing exists to avoid — attribute it.
            with telemetry.phase("ckpt_stall"):
                self._drain_writer()
                self._mgr.wait_until_finished()
            self._flush_manifests()
        finally:
            self._busy = False
            self._run_deferred_preemption()

    def close(self) -> None:
        self._drain_writer()
        with self._wcond:
            self._wstop = True
            self._wcond.notify_all()
        if self._wthread is not None:
            self._wthread.join(timeout=30)
            self._wthread = None
        self._mgr.close()
        # close() waited for in-flight saves; their manifests are now due.
        self._flush_manifests()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.wait()
        self.close()
