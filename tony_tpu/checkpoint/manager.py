"""Async sharded checkpointing for train state.

The reference delegates checkpointing entirely to user code (SURVEY.md §5:
"TonY provides no checkpoint manager; resume-after-AM-retry works only
because user scripts re-read checkpoints from HDFS" — e.g.
``MonitoredTrainingSession(checkpoint_dir=...)`` in
``tony-examples/mnist-tensorflow``). A TPU framework cannot: multi-host
sharded state needs coordinated, topology-aware save/restore. This wraps
orbax — async so the save overlaps the next training steps, sharding-aware
so each host writes only its own shards and restore re-lays-out onto any
mesh with matching global shapes.

Resume contract with the coordinator's whole-job retry (sessionId epochs,
``ApplicationMaster.java:356-371``): user scripts call ``latest_step()`` at
startup and restore if non-None — a retried session transparently continues
from the last completed save.
"""

from __future__ import annotations

import logging
from typing import Any, Optional

log = logging.getLogger(__name__)


class CheckpointManager:
    """Thin policy wrapper over ``orbax.checkpoint.CheckpointManager``."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 save_interval_steps: int = 1, async_save: bool = True):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self._busy = False               # main thread inside an orbax call
        self._preempt: Optional[dict] = None
        self._mgr = ocp.CheckpointManager(
            ocp.path.utils.to_absolute_path(str(directory))
            if hasattr(ocp.path, "utils") else str(directory),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=async_save,
            ))

    def save(self, step: int, state: Any, force: bool = False) -> bool:
        """Queue an (async) save; returns False when skipped by the
        save_interval_steps policy."""
        self._busy = True
        try:
            return self._mgr.save(
                int(step), args=self._ocp.args.StandardSave(state),
                force=force)
        finally:
            self._busy = False
            self._run_deferred_preemption()

    def restore(self, step: Optional[int], like: Any) -> Any:
        """Restore ``step`` (or the latest when None) with the shardings of
        ``like`` — pass the freshly-initialized state (or an eval_shape of
        it with NamedSharding leaves) so every shard lands on its device."""
        import jax

        target = jax.tree.map(
            lambda x: (jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
                       if hasattr(x, "sharding") else x), like)
        step = int(step) if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError("no checkpoint to restore")
        return self._mgr.restore(
            step, args=self._ocp.args.StandardRestore(target))

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def install_preemption_handler(self, snapshot, exit_code: int = 143
                                   ) -> None:
        """Save-on-SIGTERM: when the job is being torn down (force-kill,
        epoch reset, slice teardown), synchronously save the state
        ``snapshot()`` returns, then exit.

        This is the consumer of the kill chain's TERM→grace→KILL contract
        (executor forwards SIGTERM to the user process group and backends
        honour a grace window — utils/proc.py, cluster/*): the handler
        gets the grace to make one final durable save, so a resumed job
        loses zero completed steps instead of rolling back to the last
        periodic save. ``snapshot`` must return ``(step, state)`` and be
        cheap to call from the main thread (it runs between Python
        bytecodes — a jitted step in flight completes first).

        Install from the MAIN thread of the training process. Exits with
        ``exit_code`` (default 143 = 128+SIGTERM, what the supervisor
        expects of a TERM'd task).
        """
        import signal

        self._preempt = {"fired": False, "deferred": False,
                         "snapshot": snapshot, "exit_code": exit_code}

        def _handler(signum, frame):
            st = self._preempt
            if st["fired"]:
                # Teardown delivers TERM more than once (the executor
                # forwards it AND the backend signals the user group
                # directly) — first one wins, the rest no-op.
                return
            if self._busy:
                # TERM landed while the main thread is INSIDE an orbax
                # call (a periodic save/wait): a re-entrant save would
                # corrupt the in-flight write ("Executor shutdown has
                # been called"). Defer — save()/wait() run the final
                # save the moment the in-flight call completes.
                st["deferred"] = True
                return
            st["fired"] = True
            self._do_preemption_save()

        signal.signal(signal.SIGTERM, _handler)

    def _run_deferred_preemption(self) -> None:
        st = self._preempt
        if st is not None and st["deferred"] and not st["fired"]:
            st["fired"] = True
            self._do_preemption_save()

    def _do_preemption_save(self) -> None:
        import sys

        st = self._preempt
        try:
            step, state = st["snapshot"]()
            log.warning("SIGTERM: saving preemption checkpoint at step %s",
                        step)
            self.save(int(step), state, force=True)
            self.wait()
            log.warning("preemption checkpoint durable; exiting")
        except Exception:  # noqa: BLE001 — still exit promptly
            log.exception("preemption save failed")
        sys.exit(st["exit_code"])

    def wait(self) -> None:
        """Block until queued async saves are durable (call before exit)."""
        self._busy = True
        try:
            self._mgr.wait_until_finished()
        finally:
            self._busy = False
            self._run_deferred_preemption()

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.wait()
        self.close()
