from tony_tpu.checkpoint.manager import CheckpointManager  # noqa: F401
