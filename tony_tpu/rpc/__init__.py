from tony_tpu.rpc.wire import RpcServer, RpcClient, RpcError  # noqa: F401
