"""Control-plane RPC: length-framed msgpack request/response over TCP.

Fills the role of the reference's Hadoop-IPC + protobuf2 control plane
(``ApplicationRpcServer.java:116-135`` server thread; retry-wrapped singleton
client ``ApplicationRpcClient.java:47-76``; 7-method service
``tensorflow_cluster_service_protos.proto:11-19`` plus the Writable metrics
channel ``rpc/MetricsRpc.java``). Differences, on purpose:

- One transport for both the application and metrics surfaces (namespaced
  methods) instead of two RPC engines on two ports — there is no Hadoop
  Writable legacy to carry here.
- msgpack framing instead of protobuf: no codegen step, and the control plane
  moves kilobytes, not tensors — the data plane is XLA collectives over
  ICI/DCN, never this channel (SURVEY.md §2.4).
- Optional shared-secret auth replaces the ClientToAMToken secret manager
  (``ApplicationMaster.java:433-452``).

Frame format: 4-byte big-endian length, then a msgpack map.
Request:  {"id": int, "method": str, "args": {...}, "token": str?}
Response: {"id": int, "ok": bool, "result": any} or {"id", "ok": False, "error": str}
"""

from __future__ import annotations

import logging
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Dict, Optional, Tuple

import msgpack

log = logging.getLogger(__name__)

_MAX_FRAME = 64 * 1024 * 1024


class RpcError(RuntimeError):
    pass


class AuthError(RpcError):
    pass


def _send_frame(sock: socket.socket, obj: Any) -> None:
    data = msgpack.packb(obj, use_bin_type=True)
    sock.sendall(struct.pack(">I", len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed connection")
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket) -> Any:
    (length,) = struct.unpack(">I", _recv_exact(sock, 4))
    if length > _MAX_FRAME:
        raise RpcError(f"frame too large: {length}")
    return msgpack.unpackb(_recv_exact(sock, length), raw=False)


class RpcServer:
    """Threaded TCP server dispatching methods on a service object.

    Reference: ``ApplicationRpcServer`` runs as a daemon thread inside the AM
    (``ApplicationMaster.java:402``); here likewise inside the coordinator.
    Any public method of ``service`` becomes callable; a method named
    ``ns__method`` is addressed as ``"ns.method"``.
    """

    def __init__(self, service: Any, host: str = "127.0.0.1", port: int = 0,
                 token: Optional[str] = None):
        self._service = service
        self._token = token
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:  # one connection, many requests
                sock = self.request
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                while True:
                    try:
                        req = _recv_frame(sock)
                    except (ConnectionError, OSError):
                        return
                    resp = outer._dispatch(req)
                    try:
                        _send_frame(sock, resp)
                    except OSError:
                        return

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    def _dispatch(self, req: Dict[str, Any]) -> Dict[str, Any]:
        rid = req.get("id", 0)
        try:
            if self._token is not None and req.get("token") != self._token:
                raise AuthError("invalid or missing auth token")
            method = str(req.get("method", "")).replace(".", "__")
            if method.startswith("_"):
                raise RpcError(f"no such method: {req.get('method')}")
            fn = getattr(self._service, method, None)
            if fn is None or not callable(fn):
                raise RpcError(f"no such method: {req.get('method')}")
            result = fn(**(req.get("args") or {}))
            return {"id": rid, "ok": True, "result": result}
        except Exception as e:  # noqa: BLE001 — must never kill the server loop
            if not isinstance(e, RpcError):
                log.exception("rpc handler error in %s", req.get("method"))
            return {"id": rid, "ok": False, "error": f"{type(e).__name__}: {e}"}

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.server_address  # type: ignore[return-value]

    @property
    def port(self) -> int:
        return self.address[1]

    def start(self) -> None:
        if self._thread is not None:  # idempotent
            return
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="tony-rpc-server",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(timeout=5)
        # A stopped server cannot be restarted (socket closed); reset the
        # idempotence guard so a future start() fails loudly in serve_forever
        # rather than silently no-op'ing.
        self._thread = None


class RpcClient:
    """Persistent-connection client with bounded reconnect retries.

    Reference retry policy: up to 10 attempts, 2 s fixed sleep
    (``ApplicationRpcClient.java:66-76``); configurable here because tests
    want fast failure.
    """

    def __init__(self, host: str, port: int, token: Optional[str] = None,
                 max_retries: int = 10, retry_sleep_s: float = 2.0,
                 connect_timeout_s: float = 10.0):
        self._addr = (host, port)
        self._token = token
        self._max_retries = max_retries
        self._retry_sleep_s = retry_sleep_s
        self._connect_timeout_s = connect_timeout_s
        self._sock: Optional[socket.socket] = None
        self._id = 0
        self._lock = threading.Lock()

    def _connect(self) -> socket.socket:
        sock = socket.create_connection(self._addr,
                                        timeout=self._connect_timeout_s)
        sock.settimeout(None)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def call(self, method: str, **args: Any) -> Any:
        last_err: Optional[Exception] = None
        with self._lock:
            for attempt in range(self._max_retries):
                try:
                    if self._sock is None:
                        self._sock = self._connect()
                    self._id += 1
                    req = {"id": self._id, "method": method, "args": args}
                    if self._token is not None:
                        req["token"] = self._token
                    _send_frame(self._sock, req)
                    resp = _recv_frame(self._sock)
                    if not resp.get("ok"):
                        err = resp.get("error", "unknown rpc error")
                        if err.startswith("AuthError"):
                            raise AuthError(err)
                        raise RpcError(err)
                    return resp.get("result")
                except (ConnectionError, OSError) as e:
                    last_err = e
                    self._close_locked()
                    if attempt < self._max_retries - 1:
                        time.sleep(self._retry_sleep_s)
        raise RpcError(
            f"rpc {method} to {self._addr} failed after "
            f"{self._max_retries} attempts: {last_err}")

    def _close_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        with self._lock:
            self._close_locked()
