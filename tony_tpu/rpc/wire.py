"""Control-plane RPC: length-framed msgpack request/response over TCP.

Fills the role of the reference's Hadoop-IPC + protobuf2 control plane
(``ApplicationRpcServer.java:116-135`` server thread; retry-wrapped singleton
client ``ApplicationRpcClient.java:47-76``; 7-method service
``tensorflow_cluster_service_protos.proto:11-19`` plus the Writable metrics
channel ``rpc/MetricsRpc.java``). Differences, on purpose:

- One transport for both the application and metrics surfaces (namespaced
  methods) instead of two RPC engines on two ports — there is no Hadoop
  Writable legacy to carry here.
- msgpack framing instead of protobuf: no codegen step, and the control plane
  moves kilobytes, not tensors — the data plane is XLA collectives over
  ICI/DCN, never this channel (SURVEY.md §2.4).
- Optional shared-secret auth replaces the ClientToAMToken secret manager
  (``ApplicationMaster.java:433-452``) — but the secret itself NEVER
  crosses the wire: with a token configured, every frame carries an
  HMAC-SHA256 over (server nonce ‖ client nonce ‖ direction ‖ payload),
  keyed by the token. Both peers contribute per-connection entropy: the
  server's nonce rides the hello, the client's rides its first frame, and
  every MAC in either direction binds both. That gives peer
  authentication, frame integrity, and replay protection in BOTH
  directions — a recorded connection cannot be replayed to a client
  (the client's fresh nonce is absent from old response MACs) nor to a
  server (its fresh nonce is absent from old request MACs), and within
  a connection the server additionally requires strictly increasing
  request ids — without the cert-distribution burden of TLS on ephemeral
  TPU-VM gangs (TLS is available as an opt-in; see make_ssl_context).
  What HMAC alone does NOT give is confidentiality — the control plane
  carries cluster specs/metrics/exit codes, no secrets (the storage
  credential rides env, never RPC; see storage/store.py).

Wire format: 4-byte big-endian length, then a msgpack map per frame.
- hello (server → client, once per connection):
    {"tony-rpc": 3, "nonce": bytes, "auth": bool[, "g": int]}
- signed frame: {"p": <inner msgpack bytes>, "m": <hmac>}; unsigned: {"p"}
  (the client's FIRST frame additionally carries {"cn": bytes}, its
  connection nonce; all MACs use server_nonce + client_nonce)
- inner request:  {"id": int, "method": str, "args": {...}[, "gen": int]
                   [, "tc": [trace_id, span_id]]}
- inner response: {"id": int, "ok": bool, "result"| "error"[, "g": int]}

Trace context ("tc", tony_tpu/tracing.py): a traced caller stamps its
(trace_id, parent span id) into every request, next to the generation
field; the server parks it in a thread-local around dispatch so handler-
side spans stitch under the caller's span — the cross-process edge of the
per-job trace tree. Observability hooks: ``on_request`` (server) and
``on_latency`` (client) time every call for the RPC latency histograms;
both are optional and free when unset.

Generation fencing (coordinator crash recovery): a recovered coordinator
starts with a bumped, journal-persisted generation and stamps it into the
hello and every response ("g"); fenced clients stamp theirs into every
request ("gen"). Either side seeing a LOWER generation than its own is
talking to a zombie from before a recovery — the split-brain case — and
rejects with StaleGenerationError, which is terminal (never retried: a
stale peer does not become fresh by retrying). Seeing a HIGHER generation
means a legitimate successor coordinator took over: clients adopt it
(monotonically) and carry on — that is the executor re-registration path.
Generation 0 on either side means unfenced and skips all checks.
"""

from __future__ import annotations

import hmac
import hashlib
import logging
import os
import socket
import socketserver
import ssl
import struct
import threading
import time
from typing import Any, Dict, Optional, Tuple

import msgpack

from tony_tpu import faults, tracing
from tony_tpu.retry import RetryPolicy

log = logging.getLogger(__name__)


def server_tls_context(cert_path: str, key_path: str) -> ssl.SSLContext:
    """TLS context for the coordinator side (RPC server / portal): present
    ``cert_path`` (PEM), key from ``key_path``. Opt-in confidentiality on
    top of the HMAC plane — reference analogue: Hadoop IPC rode the
    cluster's SASL/token machinery (``ApplicationMaster.java:433-452``);
    here the operator ships one self-signed pair via config
    (tony.application.security.tls-*)."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert_path, key_path)
    return ctx


def client_tls_context(cert_path: str) -> ssl.SSLContext:
    """TLS context for clients (submitter, executors): PIN the server's
    certificate (self-signed pairs on ephemeral gangs have no CA and their
    IPs aren't in any SAN — pinning the exact cert is both simpler and
    stricter than hostname verification)."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_REQUIRED
    ctx.load_verify_locations(cert_path)
    return ctx

_MAX_FRAME = 64 * 1024 * 1024
_TO_SERVER = b"C"
_TO_CLIENT = b"S"


class RpcError(RuntimeError):
    pass


class AuthError(RpcError):
    pass


class RpcTimeout(RpcError):
    """A per-call send/recv deadline expired: the peer is up enough to
    hold the TCP connection but not answering — the WEDGED-coordinator
    shape, distinct from connection-refused. Classified INFRA_TRANSIENT
    (``failure_domain``) so supervisors treat it like any other transient
    infra failure rather than a user error."""

    failure_domain = "INFRA_TRANSIENT"


class FencedError(RpcError):
    """Terminal fencing rejection: the peer belongs to a superseded
    coordinator generation or a stale session epoch. Never retried —
    retrying cannot make a zombie fresh; the holder must tear itself
    down (executors: kill the user process and exit)."""


class StaleGenerationError(FencedError):
    """Generation fence specifically (see module docstring)."""


def _send_frame(sock: socket.socket, obj: Any) -> None:
    data = msgpack.packb(obj, use_bin_type=True)
    sock.sendall(struct.pack(">I", len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed connection")
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket) -> Any:
    (length,) = struct.unpack(">I", _recv_exact(sock, 4))
    if length > _MAX_FRAME:
        raise RpcError(f"frame too large: {length}")
    return msgpack.unpackb(_recv_exact(sock, length), raw=False)


def _mac(token: str, nonce: bytes, direction: bytes, payload: bytes) -> bytes:
    return hmac.new(token.encode(), nonce + direction + payload,
                    hashlib.sha256).digest()


def _send_signed(sock: socket.socket, obj: Any, token: Optional[str],
                 nonce: bytes, direction: bytes,
                 extra: Optional[Dict[str, Any]] = None) -> None:
    inner = msgpack.packb(obj, use_bin_type=True)
    frame: Dict[str, Any] = {"p": inner}
    if extra:
        frame.update(extra)
    if token:
        frame["m"] = _mac(token, nonce, direction, inner)
    _send_frame(sock, frame)


def _verify_frame(frame: Any, token: Optional[str],
                  nonce: bytes, direction: bytes) -> Any:
    if not isinstance(frame, dict) or "p" not in frame:
        raise RpcError("malformed frame (no payload)")
    inner = frame["p"]
    if token:
        mac = frame.get("m")
        if not isinstance(mac, (bytes, bytearray)) or not hmac.compare_digest(
                mac, _mac(token, nonce, direction, inner)):
            raise AuthError("bad or missing frame MAC")
    return msgpack.unpackb(inner, raw=False)


def _recv_signed(sock: socket.socket, token: Optional[str],
                 nonce: bytes, direction: bytes) -> Any:
    return _verify_frame(_recv_frame(sock), token, nonce, direction)


class RpcServer:
    """Threaded TCP server dispatching methods on a service object.

    Reference: ``ApplicationRpcServer`` runs as a daemon thread inside the AM
    (``ApplicationMaster.java:402``); here likewise inside the coordinator.
    Any public method of ``service`` becomes callable; a method named
    ``ns__method`` is addressed as ``"ns.method"``.
    """

    def __init__(self, service: Any, host: str = "127.0.0.1", port: int = 0,
                 token: Optional[str] = None,
                 tls: Optional[ssl.SSLContext] = None,
                 generation: int = 0,
                 on_superseded: Optional[Any] = None,
                 on_request: Optional[Any] = None) -> None:
        self._service = service
        self._token = token or None     # "" = unauthenticated, like None
        self._tls = tls
        # Observability hook: called (method, seconds, ok) after every
        # dispatched request, with the caller's trace context still set —
        # the coordinator feeds its latency histograms and RPC spans here.
        self._on_request = on_request
        # Coordinator generation this server speaks for (0 = unfenced).
        # Fixed for the server's lifetime: a recovery is a NEW process.
        self._generation = int(generation)
        # Called (once per observation, with the newer generation) when a
        # request proves a SUCCESSOR coordinator exists — this server is
        # the zombie side of a split brain and should stand down.
        self._on_superseded = on_superseded
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:  # one connection, many requests
                sock = self.request
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                if outer._tls is not None:
                    # Per-connection handshake (in this handler thread, so
                    # a stalling peer never blocks the accept loop); a
                    # plaintext or wrong-cert peer fails here and is
                    # dropped before any frame is read.
                    try:
                        sock = outer._tls.wrap_socket(sock, server_side=True)
                    except (ssl.SSLError, OSError) as e:
                        log.debug("TLS handshake failed from %s: %s",
                                  self.client_address, e)
                        return
                nonce = os.urandom(16)
                hello = {"tony-rpc": 3, "nonce": nonce,
                         "auth": outer._token is not None}
                if outer._generation:
                    hello["g"] = outer._generation
                try:
                    _send_frame(sock, hello)
                except OSError:
                    return
                last_id = 0
                first = True
                while True:
                    try:
                        frame = _recv_frame(sock)
                        if first:
                            # The client's first frame carries its own
                            # connection nonce; from here on every MAC
                            # (both directions) binds both nonces, so a
                            # recorded connection cannot be replayed to a
                            # fresh client — old response MACs lack this
                            # client's entropy.
                            cn = frame.get("cn", b"") \
                                if isinstance(frame, dict) else b""
                            # Exactly 16 bytes or nothing: an unauthenticated
                            # peer must not be able to inflate every HMAC for
                            # the connection's lifetime with a huge cn.
                            if isinstance(cn, (bytes, bytearray)) \
                                    and len(cn) == 16:
                                nonce = nonce + bytes(cn)
                            first = False
                        req = _verify_frame(frame, outer._token, nonce,
                                            _TO_SERVER)
                    except AuthError as e:
                        # Unauthenticated peer: say why (signed, so a
                        # legitimate client can distinguish bad-key from
                        # network damage), then drop the connection.
                        try:
                            _send_signed(
                                sock, {"id": 0, "ok": False,
                                       "error": f"AuthError: {e}"},
                                outer._token, nonce, _TO_CLIENT)
                        except OSError:
                            pass
                        return
                    except (RpcError, ConnectionError, OSError):
                        return
                    rid = req.get("id", 0) if isinstance(req, dict) else 0
                    req_gen = int(req.get("gen", 0) or 0) \
                        if isinstance(req, dict) else 0
                    if outer._token is not None and rid <= last_id:
                        # Replay of a captured frame (MAC valid, id seen):
                        # the nonce pins frames to this connection, the id
                        # ordering pins them to one use.
                        resp = {"id": rid, "ok": False,
                                "error": "AuthError: replayed request id"}
                    elif outer._generation and req_gen \
                            and req_gen < outer._generation:
                        # Frame from before a coordinator recovery: fence
                        # it out before it can touch any state. Terminal
                        # for the sender (client never retries this).
                        resp = {"id": rid, "ok": False,
                                "error": f"StaleGenerationError: frame "
                                         f"from generation {req_gen}; "
                                         f"coordinator is at generation "
                                         f"{outer._generation}"}
                    elif outer._generation and req_gen \
                            and req_gen > outer._generation:
                        # The sender has seen a NEWER coordinator: WE are
                        # the stale side of the split brain. Refuse the
                        # frame and tell the owner to stand down.
                        resp = {"id": rid, "ok": False,
                                "error": f"StaleGenerationError: this "
                                         f"coordinator (generation "
                                         f"{outer._generation}) was "
                                         f"superseded by generation "
                                         f"{req_gen}"}
                        if outer._on_superseded is not None:
                            try:
                                outer._on_superseded(req_gen)
                            except Exception:  # noqa: BLE001
                                log.exception("on_superseded callback")
                    else:
                        last_id = max(last_id, rid)
                        resp = outer._dispatch(req)
                    if outer._generation:
                        resp["g"] = outer._generation
                    try:
                        _send_signed(sock, resp, outer._token, nonce,
                                     _TO_CLIENT)
                    except OSError:
                        return

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    def _dispatch(self, req: Dict[str, Any]) -> Dict[str, Any]:
        rid = req.get("id", 0)
        # Caller's trace context rides the frame next to the generation
        # field; park it thread-locally so handler-side spans stitch under
        # the caller's span (tony_tpu/tracing.py).
        tc = req.get("tc")
        if isinstance(tc, (list, tuple)) and len(tc) == 2:
            tracing.set_rpc_context((str(tc[0]), str(tc[1])))
        t0 = time.monotonic()
        ok = True
        try:
            # Auth happened at the frame layer (_recv_signed MAC check);
            # by the time a request reaches dispatch it is authentic.
            method = str(req.get("method", "")).replace(".", "__")
            if method.startswith("_"):
                raise RpcError(f"no such method: {req.get('method')}")
            fn = getattr(self._service, method, None)
            if fn is None or not callable(fn):
                raise RpcError(f"no such method: {req.get('method')}")
            result = fn(**(req.get("args") or {}))
            return {"id": rid, "ok": True, "result": result}
        except Exception as e:  # noqa: BLE001 — must never kill the server loop
            ok = False
            if not isinstance(e, RpcError):
                log.exception("rpc handler error in %s", req.get("method"))
            return {"id": rid, "ok": False, "error": f"{type(e).__name__}: {e}"}
        finally:
            if self._on_request is not None:
                try:
                    self._on_request(str(req.get("method", "")),
                                     time.monotonic() - t0, ok)
                except Exception:  # noqa: BLE001 — observability only
                    log.exception("on_request hook")
            tracing.clear_rpc_context()

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.server_address  # type: ignore[return-value]

    @property
    def port(self) -> int:
        return self.address[1]

    def start(self) -> None:
        if self._thread is not None:  # idempotent
            return
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="tony-rpc-server",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(timeout=5)
        # A stopped server cannot be restarted (socket closed); reset the
        # idempotence guard so a future start() fails loudly in serve_forever
        # rather than silently no-op'ing.
        self._thread = None


class RpcClient:
    """Persistent-connection client with bounded reconnect retries.

    Reference retry policy: up to 10 attempts, 2 s FIXED sleep
    (``ApplicationRpcClient.java:66-76``) — which synchronizes a whole
    gang's reconnect storms onto the coordinator at the exact moment it
    is least able to serve them. Here the budget is the same shape
    (``max_retries`` attempts; ``retry_sleep_s`` caps any one sleep) but
    delays ramp exponentially with full jitter (tony_tpu/retry.py), so N
    executors retrying the same outage spread over the window instead of
    arriving in lockstep. Tests keep fast failure via small values.
    """

    def __init__(self, host: str, port: int, token: Optional[str] = None,
                 max_retries: int = 10, retry_sleep_s: float = 2.0,
                 connect_timeout_s: float = 10.0,
                 tls: Optional[ssl.SSLContext] = None,
                 generation: int = 0,
                 call_timeout_s: Optional[float] = None,
                 on_latency: Optional[Any] = None,
                 peer: str = "") -> None:
        self._addr = (host, port)
        self._token = token or None     # "" = unauthenticated, like None
        # Wire label for directional fault scoping (rpc.partition
        # peer:NAME): which service this client dials — "coordinator",
        # "pool", "fleet". Purely observational; "" = unlabelled.
        self._peer = peer
        self._tls = tls
        # (trace_id, span_id) stamped into every request ("tc") when set —
        # the caller's edge of the cross-process span tree.
        self.trace_context: Optional[Tuple[str, str]] = None
        # Observability hook: called (method, seconds) on every SUCCESSFUL
        # call with its end-to-end latency (send→response, this attempt) —
        # executors feed their client-latency histogram here.
        self._on_latency = on_latency
        # Lowest coordinator generation this client will talk to (0 =
        # unfenced). Adopted UPWARD from server hellos/responses — a
        # successor coordinator is legitimate; a lower one is a zombie.
        self._generation = int(generation)
        # Per-call send/recv deadline. Without it a wedged (accepted the
        # connection, never answers) coordinator parks the caller forever
        # — the executor heartbeat thread being the critical victim.
        self._call_timeout_s = call_timeout_s or None
        self._max_retries = max_retries
        self._retry_sleep_s = retry_sleep_s
        self._retry_policy = RetryPolicy(
            max_attempts=max(1, max_retries),
            base_delay_s=max(retry_sleep_s / 4.0, 0.001),
            max_delay_s=max(retry_sleep_s, 0.001))
        self._connect_timeout_s = connect_timeout_s
        self._sock: Optional[socket.socket] = None
        self._nonce: bytes = b""
        self._client_nonce: bytes = b""
        self._hello_pending = False
        self._id = 0
        self._lock = threading.Lock()

    def _connect(self) -> Tuple[socket.socket, bytes, bytes, int]:
        """Dial + hello handshake, touching NO shared client state —
        call() runs this OUTSIDE the frame lock (connect can block for
        the full connect timeout; holding the lock through it would park
        every other caller thread — a sanitizer hold-while-blocking
        hazard) and installs the result under the lock.

        Returns (socket, combined nonce, client nonce, peer generation).
        """
        faults.check("rpc.connect")
        sock = socket.create_connection(self._addr,
                                        timeout=self._connect_timeout_s)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if self._tls is not None:
            try:
                sock = self._tls.wrap_socket(
                    sock, server_hostname=self._addr[0])
            except (ssl.SSLError, OSError):
                sock.close()
                raise
        # The connect timeout stays armed through the hello read: a peer
        # that accepts but never greets (wrong service, pre-v2 server)
        # must error out, not deadlock the first call() forever.
        try:
            hello = _recv_frame(sock)
        except (OSError, RpcError):
            sock.close()
            raise
        # Armed for every subsequent send/recv on this connection: a
        # wedged peer surfaces as socket.timeout → RpcTimeout, not a hang.
        sock.settimeout(self._call_timeout_s)
        if not isinstance(hello, dict) or "nonce" not in hello:
            sock.close()
            raise RpcError("peer is not a tony-rpc server (no hello)")
        if self._token is not None and hello.get("tony-rpc") != 3:
            # A v2 server verifies MACs over its nonce alone; our dual-nonce
            # MACs would fail there with a misleading "bad frame MAC". Name
            # the real problem instead.
            sock.close()
            raise RpcError(
                f"peer speaks tony-rpc v{hello.get('tony-rpc')}; this "
                "authenticated client requires v3 (dual-nonce MACs)")
        # Contribute our own freshness: the combined nonce goes into every
        # MAC both ways, so recorded responses from an old connection can
        # never satisfy this one (ADVICE r4: the hello alone gave the
        # client no replay protection).
        client_nonce = os.urandom(16)
        return (sock, hello["nonce"] + client_nonce, client_nonce,
                int(hello.get("g", 0) or 0))

    def _check_peer_generation(self, peer_gen: int,
                               sock: Optional[socket.socket] = None) -> None:
        """Fence or adopt: a LOWER peer generation is a zombie coordinator
        (terminal StaleGenerationError); a higher one is a legitimate
        successor and is adopted monotonically. No-op when either side is
        unfenced (generation 0)."""
        if not peer_gen or not self._generation:
            return
        if peer_gen < self._generation:
            if sock is not None:
                sock.close()
            raise StaleGenerationError(
                f"peer at {self._addr} speaks for coordinator generation "
                f"{peer_gen}; generation {self._generation} has already "
                f"been observed — refusing the stale coordinator")
        self._generation = max(self._generation, peer_gen)

    @property
    def generation(self) -> int:
        """Highest coordinator generation observed (0 = unfenced)."""
        return self._generation

    def call(self, method: str, **args: Any) -> Any:
        last_err: Optional[Exception] = None
        # The lock serializes frames on the shared socket, per ATTEMPT —
        # never across a sleep. Holding it through the backoff (the old
        # shape) parked every other caller behind one caller's outage;
        # the lock sanitizer (devtools/sanitizer.py) flags exactly that
        # hold-while-blocking hazard.
        for attempt in range(self._max_retries):
            slow = faults.fire_amount("rpc.slow")
            if slow:
                # Injected control-plane latency: the frame still goes
                # through, just late — lands in the latency histograms
                # and trace spans, never in a retry. Before the timed
                # send, and before the lock: a slow wire must not block
                # other callers' frames.
                time.sleep(slow)
            try:
                # Dial outside the lock (see _connect). The unlocked
                # read of _sock can race another caller — the loser's
                # fresh socket is closed at install time below.
                conn = self._connect() if self._sock is None else None
                with self._lock:
                    if conn is not None:
                        sock, nonce, client_nonce, peer_gen = conn
                        if self._sock is None:
                            self._check_peer_generation(peer_gen, sock)
                            self._sock = sock
                            self._nonce = nonce
                            self._client_nonce = client_nonce
                            self._hello_pending = True
                            # Request ids double as the anti-replay
                            # sequence; reset with the fresh nonce.
                            self._id = 0
                        else:
                            sock.close()    # raced: reuse the winner's
                    if self._sock is None:
                        # Concurrent caller closed the connection between
                        # our unlocked check and the lock: retry cleanly.
                        raise ConnectionResetError(
                            "connection closed by a concurrent caller")
                    # A dropped frame surfaces as a connection error and
                    # rides the same reconnect+backoff path a real reset
                    # takes (tony_tpu/faults.py site table).
                    faults.check("rpc.send")
                    # Asymmetric partition, request direction: the frame
                    # dies BEFORE the send — the callee never sees it.
                    faults.check_partition("rpc.partition", "c2s",
                                           self._peer)
                    t_call = time.monotonic()
                    self._id += 1
                    req = {"id": self._id, "method": method, "args": args}
                    if self._generation:
                        req["gen"] = self._generation
                    if self.trace_context is not None:
                        req["tc"] = list(self.trace_context)
                    extra = {"cn": self._client_nonce} \
                        if self._token and self._hello_pending else None
                    _send_signed(self._sock, req, self._token, self._nonce,
                                 _TO_SERVER, extra=extra)
                    self._hello_pending = False
                    # Asymmetric partition, response direction: the
                    # request was DELIVERED — the callee processes it and
                    # its side effects land — but the response never
                    # comes back. The caller sees a reset and retries,
                    # so non-idempotent handlers rehearse the
                    # duplicate-delivery shape a real one-way cut causes.
                    faults.check_partition("rpc.partition", "s2c",
                                           self._peer)
                    # Response MAC proves the SERVER holds the secret too
                    # (mutual auth); a mismatch raises AuthError and is
                    # not retried.
                    resp = _recv_signed(self._sock, self._token,
                                        self._nonce, _TO_CLIENT)
                    if self._token is not None and \
                            resp.get("id") not in (self._id, 0):
                        # Freshness: a recorded signed response from an
                        # earlier request must not answer this one (id 0
                        # = the server's pre-dispatch auth error frame).
                        raise AuthError(
                            f"response id {resp.get('id')} does not match "
                            f"request {self._id} (replayed response?)")
                    self._check_peer_generation(
                        int(resp.get("g", 0) or 0)
                        if isinstance(resp, dict) else 0)
                    if not resp.get("ok"):
                        err = resp.get("error", "unknown rpc error")
                        if err.startswith("AuthError"):
                            raise AuthError(err)
                        if err.startswith("StaleGenerationError"):
                            raise StaleGenerationError(err)
                        if err.startswith("FencedError"):
                            raise FencedError(err)
                        raise RpcError(err)
                    if self._on_latency is not None:
                        try:
                            self._on_latency(method,
                                             time.monotonic() - t_call)
                        except Exception:  # noqa: BLE001 — observability only
                            pass
                    return resp.get("result")
            except (AuthError, FencedError):
                # Both are terminal verdicts about THIS peer/process
                # pair — retrying cannot change either.
                self.close()
                raise
            except (ConnectionError, OSError) as e:
                last_err = e
                self.close()
                if attempt < self._max_retries - 1:
                    time.sleep(self._retry_policy.delay_s(attempt))
        if isinstance(last_err, socket.timeout):
            raise RpcTimeout(
                f"rpc {method} to {self._addr} timed out after "
                f"{self._max_retries} attempts of {self._call_timeout_s}s "
                f"each [INFRA_TRANSIENT]: the peer holds the connection "
                f"but does not answer")
        raise RpcError(
            f"rpc {method} to {self._addr} failed after "
            f"{self._max_retries} attempts: {last_err}")

    def _close_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        with self._lock:
            self._close_locked()
