"""Framework runtimes: translate the cluster spec into each ML framework's
rendezvous environment.

Reference model: the framework switch in ``TaskExecutor.java:161-207`` —
TENSORFLOW exports TF_CONFIG/CLUSTER_SPEC, PYTORCH exports
INIT_METHOD/RANK/WORLD, MXNET exports DMLC_*, HOROVOD exports nothing —
with the spec-formatting logic in ``util/Utils.java`` (``constructTFConfig``
:491, ``parseClusterSpecForPytorch`` :575, MXNet :587-609).

New here: **JAXRuntime**, the TPU-native first-class citizen. It replaces all
the dialects with ``jax.distributed.initialize`` bootstrap variables computed
from the same cluster spec, so one rendezvous mechanism serves every JAX job
(SURVEY.md §2.4). GENERIC serves arbitrary gang topologies (the Ray pattern,
``tony-examples/ray-on-tony``) by exporting only CLUSTER_SPEC.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Type

from tony_tpu import constants
from tony_tpu.conf.config import TonyTpuConfig


@dataclasses.dataclass
class TaskIdentity:
    job_name: str
    index: int
    task_num: int
    is_chief: bool
    port: int  # reserved rendezvous port of THIS task


def flatten_spec(cluster_spec: Dict[str, List[str]]) -> List[str]:
    """Deterministic global ordering of tasks: chief first, then worker, then
    remaining jobtypes alphabetically; within a jobtype by index. Defines the
    global-rank contract shared by JAX/PyTorch runtimes."""
    order = []
    names = sorted(cluster_spec)
    for special in (constants.CHIEF_JOB_NAME, constants.WORKER_JOB_NAME):
        if special in cluster_spec:
            order.append(special)
    order.extend(n for n in names if n not in order)
    flat: List[str] = []
    for name in order:
        flat.extend(f"{name}:{i}" for i in range(len(cluster_spec[name])))
    return flat


def task_addr(cluster_spec: Dict[str, List[str]], task_id: str) -> str:
    job, _, idx = task_id.partition(":")
    return cluster_spec[job][int(idx)]


class Runtime:
    name = "generic"

    def build_env(self, cluster_spec: Dict[str, List[str]],
                  me: TaskIdentity, conf: TonyTpuConfig) -> Dict[str, str]:
        """Environment exported to the user process. Every runtime also gets
        CLUSTER_SPEC + the tony-tpu global-rank contract."""
        flat = flatten_spec(cluster_spec)
        my_id = f"{me.job_name}:{me.index}"
        env = {
            constants.CLUSTER_SPEC: json.dumps(cluster_spec, sort_keys=True),
            constants.GLOBAL_RANK: str(flat.index(my_id)),
            constants.GLOBAL_WORLD: str(len(flat)),
            constants.TASK_PORT: str(me.port),
        }
        env.update(self.framework_env(cluster_spec, me, conf))
        return env

    def framework_env(self, cluster_spec: Dict[str, List[str]],
                      me: TaskIdentity, conf: TonyTpuConfig) -> Dict[str, str]:
        return {}


_REGISTRY: Dict[str, Type[Runtime]] = {}


def register(cls: Type[Runtime]) -> Type[Runtime]:
    _REGISTRY[cls.name] = cls
    return cls


def get_runtime(name: str) -> Runtime:
    """Look up a runtime by ``tony.application.framework`` value (reference
    ``MLFramework`` enum, ``TonyConfigurationKeys.java:12-17``)."""
    # Import side-effect registration.
    from tony_tpu.runtimes import frameworks  # noqa: F401

    cls = _REGISTRY.get(name.lower())
    if cls is None:
        raise ValueError(
            f"unknown framework {name!r}; known: {sorted(_REGISTRY)}")
    return cls()


register(Runtime)
