from tony_tpu.runtimes.base import Runtime, TaskIdentity, get_runtime  # noqa: F401
