"""Concrete framework runtimes (see base.py module docstring for the map to
``TaskExecutor.java:161-207``)."""

from __future__ import annotations

import json
import os
from typing import Dict, List

from tony_tpu import constants
from tony_tpu.conf.config import TonyTpuConfig
from tony_tpu.runtimes.base import (Runtime, TaskIdentity, flatten_spec,
                                    register)


@register
class JaxRuntime(Runtime):
    """TPU-native runtime: bootstrap for ``jax.distributed.initialize``.

    The cluster-spec barrier already guarantees every process knows every
    host:port, so the coordination service address is simply the
    globally-first task's advertised endpoint; process ids follow the
    global-rank contract. This single mechanism replaces TF_CONFIG /
    MASTER_ADDR / DMLC_* for JAX jobs (SURVEY.md §2.4), and XLA collectives
    over ICI/DCN become the data plane.
    """

    name = "jax"

    def framework_env(self, cluster_spec: Dict[str, List[str]],
                      me: TaskIdentity, conf: TonyTpuConfig) -> Dict[str, str]:
        flat = flatten_spec(cluster_spec)
        my_id = f"{me.job_name}:{me.index}"
        rank = flat.index(my_id)
        job0, _, idx0 = flat[0].partition(":")
        coordinator = cluster_spec[job0][int(idx0)]
        env = {
            constants.JAX_COORDINATOR_ADDRESS: coordinator,
            constants.JAX_NUM_PROCESSES: str(len(flat)),
            constants.JAX_PROCESS_ID: str(rank),
        }
        from tony_tpu.conf import keys as K

        # Persistent XLA compile cache (VERDICT r4 weak #3): a HOST-stable
        # path, so the second job on a TPU VM skips the first's compiles —
        # this is most of the 40 s cold submit-to-first-step. The user's
        # own env wins (task env inherits the executor's os.environ, which
        # carries EXECUTION_ENV); empty key disables.
        cache_dir = str(conf.get(K.JAX_COMPILE_CACHE_DIR, "") or "").strip()
        if cache_dir and constants.JAX_COMPILATION_CACHE_DIR \
                not in os.environ:
            env[constants.JAX_COMPILATION_CACHE_DIR] = \
                os.path.expanduser(cache_dir)
        if len(flat) > 1 and os.environ.get(
                "JAX_PLATFORMS", "").strip().lower() == "cpu":
            # Multi-process CPU gangs (the virtual-mesh test substrate)
            # need an explicit cross-process collectives backend on jax
            # versions where the CPU default is "none" — without it every
            # sharded jit fails with "Multiprocess computations aren't
            # implemented on the CPU backend". Harmless where gloo is
            # already the default; user env wins.
            env.setdefault("JAX_CPU_COLLECTIVES_IMPLEMENTATION", "gloo")
        return env


@register
class TensorFlowRuntime(Runtime):
    """TF_CONFIG + legacy CLUSTER_SPEC (reference ``Utils.constructTFConfig``
    :491-501 and ``TaskExecutor.java:161-168``)."""

    name = "tensorflow"

    def framework_env(self, cluster_spec: Dict[str, List[str]],
                      me: TaskIdentity, conf: TonyTpuConfig) -> Dict[str, str]:
        tf_config = {
            "cluster": cluster_spec,
            "task": {"type": me.job_name, "index": me.index},
            "environment": "cloud",
        }
        return {constants.TF_CONFIG: json.dumps(tf_config, sort_keys=True)}


@register
class PyTorchRuntime(Runtime):
    """torch.distributed TCP rendezvous (reference ``TaskExecutor.java:169-179``
    + ``Utils.parseClusterSpecForPytorch`` :575-585): INIT_METHOD points at the
    globally-first task; RANK/WORLD follow the global ordering. Also exports
    MASTER_ADDR/MASTER_PORT/WORLD_SIZE for modern torchrun-style scripts and
    torch_xla's xla:// rendezvous."""

    name = "pytorch"

    def framework_env(self, cluster_spec: Dict[str, List[str]],
                      me: TaskIdentity, conf: TonyTpuConfig) -> Dict[str, str]:
        flat = flatten_spec(cluster_spec)
        rank = flat.index(f"{me.job_name}:{me.index}")
        job0, _, idx0 = flat[0].partition(":")
        master = cluster_spec[job0][int(idx0)]
        host, _, port = master.rpartition(":")
        return {
            constants.INIT_METHOD: f"tcp://{master}",
            constants.RANK: str(rank),
            constants.WORLD: str(len(flat)),
            constants.MASTER_ADDR: host,
            constants.MASTER_PORT: port,
            constants.WORLD_SIZE: str(len(flat)),
        }


@register
class MXNetRuntime(Runtime):
    """DMLC_* parameter-server env (reference ``TaskExecutor.java:180-200`` +
    ``Utils`` :587-609): the ``scheduler`` task's address is the PS root; roles
    come from jobtype names scheduler/server/worker."""

    name = "mxnet"

    def framework_env(self, cluster_spec: Dict[str, List[str]],
                      me: TaskIdentity, conf: TonyTpuConfig) -> Dict[str, str]:
        sched = cluster_spec.get(constants.SCHEDULER_JOB_NAME, [])
        if not sched:
            raise ValueError("mxnet runtime requires a 'scheduler' jobtype")
        host, _, port = sched[0].rpartition(":")
        return {
            constants.DMLC_PS_ROOT_URI: host,
            constants.DMLC_PS_ROOT_PORT: port,
            constants.DMLC_ROLE: me.job_name,
            constants.DMLC_NUM_SERVER: str(
                len(cluster_spec.get(constants.SERVER_JOB_NAME, []))),
            constants.DMLC_NUM_WORKER: str(
                len(cluster_spec.get(constants.WORKER_JOB_NAME, []))),
            constants.DMLC_USE_KUBERNETES: "0",
        }


@register
class HorovodRuntime(Runtime):
    """Horovod does its own MPI/gloo rendezvous inside the user command —
    nothing to export (reference ``TaskExecutor.java:201-204``)."""

    name = "horovod"
