"""Multi-host input pipeline: per-process shards assembled into global
device arrays.

The reference has no data subsystem — feeding was entirely the user
script's problem (SURVEY.md §2.2 examples read MNIST locally per worker).
On TPU the idiomatic shape is: every process loads ONLY its slice of the
global batch, and `jax.make_array_from_process_local_data` assembles the
logical global array laid out by a `NamedSharding` — no host ever
materializes the full batch, and the arrays land already sharded for the
train step (scaling-book input recipe).

Pieces:
- ``global_batch_sharding(mesh)`` — the standard batch layout (leading
  dim over ``dcn_dp × dp × fsdp``; alias of ``parallel.mesh
  .batch_sharding``, the single source of truth).
- ``ShardedBatchIterator`` — wraps any per-sample source callable and
  yields globally-sharded pytrees; deterministic per (seed, step,
  process), so restarts resume identically (checkpoint/resume
  composability).
- ``synthetic_lm_batches`` — the zero-dependency token source used by
  benches/examples (swap for a real tokenized dataset reader).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
from typing import Any, Callable, Dict, Iterator, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from tony_tpu import telemetry
from tony_tpu.parallel.mesh import batch_sharding as global_batch_sharding


def process_batch_slice(global_batch: int, rank: Optional[int] = None,
                        world: Optional[int] = None) -> slice:
    """This process's contiguous row range of the global batch.

    ``rank``/``world`` default to the jax distributed runtime; pass them
    explicitly for elastic gangs (coordinator/elastic.py): after a
    resize the executor re-exports the DENSE rank and world
    (TASK_INDEX/TASK_NUM, TONY_GLOBAL_RANK/TONY_GLOBAL_WORLD) and the
    same global batch re-splits across the surviving ranks — every row
    of every step is consumed by exactly one process at whatever world
    size executed that step, so a shrink drops no sample and duplicates
    none."""
    n = int(world) if world is not None else jax.process_count()
    i = int(rank) if rank is not None else jax.process_index()
    if not 0 <= i < n:
        raise ValueError(f"rank {i} outside world of {n}")
    if global_batch % n:
        raise ValueError(
            f"global batch {global_batch} not divisible by process count {n}")
    per = global_batch // n
    return slice(i * per, (i + 1) * per)


@dataclasses.dataclass
class ShardedBatchIterator:
    """Yield globally-sharded batches from a per-process loader.

    ``load_local(step, rows)`` returns this process's rows of the global
    batch for ``step`` as a pytree of numpy/jax arrays with leading dim
    ``rows.stop - rows.start``. The iterator assembles them into global
    ``jax.Array``s laid out by ``shardings`` (a pytree matching the batch,
    or a single sharding applied to every leaf).

    ``prefetch`` (default 2) double-buffers: a daemon thread loads and
    device-puts batch N+1..N+prefetch while step N computes, so the host
    read + H2D transfer hide behind the accelerator (the training loop's
    ``__next__`` returns an already-device-resident batch). 0 = fully
    synchronous (the pre-r5 behavior). ``step`` reports the next step the
    CONSUMER will see — checkpoint/resume keys off consumed batches, not
    what the buffer got ahead to."""

    mesh: Mesh
    global_batch: int
    load_local: Callable[[int, slice], Dict[str, Any]]
    shardings: Optional[Any] = None
    start_step: int = 0
    prefetch: int = 2

    def __post_init__(self):
        self._step = self.start_step        # next step the WORKER loads
        self._consumed = self.start_step    # next step the CONSUMER gets
        self._rows = process_batch_slice(self.global_batch)
        self._q: Optional[queue.Queue] = None
        self._worker: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()

    @property
    def step(self) -> int:
        return self._consumed

    def __iter__(self) -> Iterator[Dict[str, Any]]:
        return self

    def _assemble(self, step: int) -> Dict[str, Any]:
        local = self.load_local(step, self._rows)

        def to_global(x, sharding):
            return jax.make_array_from_process_local_data(
                sharding, np.asarray(x))

        if self.shardings is None or isinstance(self.shardings,
                                                NamedSharding):
            default = self.shardings
            return jax.tree.map(
                lambda x: to_global(
                    x, default or global_batch_sharding(
                        self.mesh, extra_dims=np.asarray(x).ndim - 1)),
                local)
        return jax.tree.map(to_global, local, self.shardings)

    def _worker_loop(self, stop: threading.Event, q: "queue.Queue",
                     step: int) -> None:
        # This generation's queue/event/step arrive as ARGUMENTS, bound
        # by __next__ at Thread construction: a worker that outlives a
        # close()+restart (join timeout) must keep talking to ITS queue,
        # never the successor's — and must not read or mutate the shared
        # step counter either (ADVICE r5: a late `self._step += 1` from
        # an abandoned worker made the restarted one silently skip a
        # batch). Snapshotting inside the loop body was not enough: an
        # abandoned worker that had not yet been SCHEDULED when the
        # restart happened would snapshot the successor's state and feed
        # duplicate batches into the new queue.
        while not stop.is_set():
            try:
                item = self._assemble(step)
                step += 1
            except BaseException as e:  # noqa: BLE001 — surface on get()
                item = _PrefetchError(e)
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.2)
                    break
                except queue.Full:
                    continue
            if isinstance(item, _PrefetchError):
                return                  # consumer re-raises; don't spin

    def __next__(self) -> Dict[str, Any]:
        # Step-time attribution rides for free: the consumer-side wait —
        # the whole assemble when synchronous, the queue wait when the
        # prefetch worker is behind, ~0 when it is ahead — IS the
        # training loop's input stall, telemetry's data_wait phase.
        if self.prefetch <= 0:
            with telemetry.phase("data_wait"):
                batch = self._assemble(self._consumed)
            self._consumed += 1
            return batch
        if self._worker is None:
            # Fresh event per worker: a close() (or the error path below)
            # sets the old one, and a restarted worker must not inherit a
            # stop signal it would obey before producing anything (the
            # consumer's q.get() would deadlock).
            self._stop_evt = threading.Event()
            self._step = self._consumed    # resume where the consumer is
            self._q = queue.Queue(maxsize=self.prefetch)
            self._worker = threading.Thread(
                target=self._worker_loop, name="tony-data-prefetch",
                args=(self._stop_evt, self._q, self._step),
                daemon=True)
            self._worker.start()
        with telemetry.phase("data_wait"):
            item = self._q.get()
        if isinstance(item, _PrefetchError):
            self.close()
            raise item.exc
        self._consumed += 1
        return item

    def close(self) -> None:
        """Stop the prefetch thread (idempotent). Iterators die with their
        (daemon) thread anyway; close() makes teardown deterministic for
        tests and bounded-lifetime loops."""
        self._stop_evt.set()
        if self._worker is not None:
            # Unblock a worker parked on a full queue.
            try:
                while True:
                    self._q.get_nowait()
            except queue.Empty:
                pass
            self._worker.join(timeout=5)
            self._worker = None


class _PrefetchError:
    """Exception envelope crossing the prefetch queue."""

    def __init__(self, exc: BaseException):
        self.exc = exc


def synthetic_lm_batches(mesh: Mesh, global_batch: int, seq: int,
                         vocab_size: int, seed: int = 0,
                         start_step: int = 0) -> ShardedBatchIterator:
    """Deterministic synthetic token batches: row ``r`` of step ``s`` is a
    pure function of (seed, s, r), so any process layout — and any restart
    — sees the same global batch."""

    def load_local(step: int, rows: slice) -> Dict[str, Any]:
        out = np.empty((rows.stop - rows.start, seq), np.int32)
        for j, r in enumerate(range(rows.start, rows.stop)):
            rng = np.random.default_rng(
                np.random.SeedSequence([seed, step, r]))
            out[j] = rng.integers(0, vocab_size, size=seq, dtype=np.int32)
        return {"tokens": out}

    return ShardedBatchIterator(mesh=mesh, global_batch=global_batch,
                                load_local=load_local,
                                start_step=start_step)


class TokenFileDataset:
    """Memory-mapped flat token corpus (the nanoGPT/MaxText ``.bin``
    shape: one contiguous array of token ids, uint16 or uint32).

    Each (step, row) of the global batch reads a ``seq``-token window at
    a position that is a pure function of (seed, step, row) — so every
    process computes ONLY its rows (mmap pages the bytes it touches, no
    host ever loads the corpus), any process layout sees the same global
    batch, and a restart at ``start_step`` resumes the identical stream
    (the checkpoint/resume contract of ``ShardedBatchIterator``). Random
    windows are the standard LM pretraining sampling; pair with
    ``write_token_file`` for building corpora in tests/tools."""

    def __init__(self, path: str, seq: int, dtype=np.uint16,
                 seed: int = 0):
        # NB: the seed must be explicit, never derived from hash(path) —
        # Python string hashing is salted per process, which would hand
        # every host a different "global" batch.
        self.path = path
        self.seq = seq
        self.seed = seed
        self.tokens = np.memmap(path, dtype=dtype, mode="r")
        # A window of exactly ``seq`` tokens is one complete sample — the
        # loss shifts inside the batch (causal_lm_loss: tokens[:, 1:]).
        if len(self.tokens) < seq:
            raise ValueError(
                f"{path}: corpus has {len(self.tokens)} tokens, need at "
                f"least seq = {seq}")

    def load_local(self, step: int, rows: slice) -> Dict[str, Any]:
        n = rows.stop - rows.start
        out = np.empty((n, self.seq), np.int32)
        span = len(self.tokens) - self.seq + 1   # every window, incl. last
        for j, r in enumerate(range(rows.start, rows.stop)):
            rng = np.random.default_rng(
                np.random.SeedSequence([self.seed, step, r]))
            off = int(rng.integers(0, span))
            out[j] = self.tokens[off:off + self.seq].astype(np.int32)
        return {"tokens": out}


def token_file_batches(mesh: Mesh, path: str, global_batch: int, seq: int,
                       dtype=np.uint16, seed: int = 0,
                       start_step: int = 0) -> ShardedBatchIterator:
    """Globally-sharded LM batches from a memory-mapped token file."""
    ds = TokenFileDataset(path, seq, dtype=dtype, seed=seed)
    return ShardedBatchIterator(mesh=mesh, global_batch=global_batch,
                                load_local=ds.load_local,
                                start_step=start_step)


def pack_documents(docs, seq: int, eos_id: int, pad_id: int = 0):
    """Pack variable-length tokenized documents into fixed [N, seq] rows —
    the shape XLA wants (static; no per-batch padding waste).

    GPT-style greedy packing: documents are concatenated, each terminated
    by ``eos_id``, and the stream is sliced into rows of ``seq``. Returns
    ``(tokens, loss_mask)`` int32/float32 arrays where the mask is 0 only
    on the final row's padding — next-token targets crossing a document
    boundary stay in the loss (standard pretraining practice; the EOS
    token is what the model learns as the boundary). Note: attention also
    crosses packed-document boundaries (no segment masking) — acceptable
    for pretraining, not for SFT-style strict isolation.

    Deterministic and order-preserving, so every process packing the same
    corpus sees identical rows (the ShardedBatchIterator contract). Feed
    the result through ``write_token_file``/``TokenFileDataset`` for the
    mmap path, or slice rows directly for small corpora.
    """
    if seq < 2:
        raise ValueError(f"seq must be >= 2, got {seq}")
    eos = np.asarray([eos_id], np.int32)
    # Vectorized concatenation — a boxed-int Python list would cost ~28
    # bytes/token and dominate wall time on real (1e8+ token) corpora.
    pieces: list = []
    for d in docs:
        pieces.append(np.asarray(d, np.int32).ravel())
        pieces.append(eos)
    if not pieces:
        raise ValueError("no documents to pack")
    stream = np.concatenate(pieces)
    n = -(-len(stream) // seq)
    flat = np.full((n * seq,), pad_id, np.int32)
    flat[:len(stream)] = stream
    mask = np.zeros((n * seq,), np.float32)
    mask[:len(stream)] = 1.0
    return flat.reshape(n, seq), mask.reshape(n, seq)


def write_token_file(path: str, tokens: "np.ndarray",
                     dtype=np.uint16) -> str:
    """Write a flat token array as a ``.bin`` corpus (tooling/tests).
    Ids that overflow ``dtype`` fail loudly — uint16 wraps 128k-vocab ids
    silently otherwise."""
    arr = np.asarray(tokens)
    if arr.ndim != 1:
        raise ValueError(f"corpus must be flat, got shape {arr.shape}")
    info = np.iinfo(dtype)
    if arr.size and (arr.min() < info.min or arr.max() > info.max):
        raise ValueError(
            f"token ids [{arr.min()}, {arr.max()}] overflow {np.dtype(dtype)}"
            f" [{info.min}, {info.max}] — use dtype=np.uint32")
    arr.astype(dtype).tofile(path)
    return path
