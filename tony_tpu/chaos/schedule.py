"""Seeded schedule planner: (seed, index, suite) -> injection set.

The planner is a PURE function of its three inputs — no wall clock, no
ambient RNG, no environment. That is the whole contract: `chaos replay`
re-plans from the artifact's (seed, index, suite) triple and must get a
bit-identical schedule back, and a shrunk schedule's surviving
injections keep their specs verbatim (the ddmin operates on the planned
list, never re-rolls it).

Two layers of determinism compose:

* the PLAN — which sites, which specs — comes from
  ``random.Random(f"{seed}:{index}:{suite}")`` here;
* the per-call DECISIONS of ``prob:P`` specs come from the injector's
  stable hash (faults._SiteRule._hash_draw), seeded with
  :func:`fault_seed` so every run of the same schedule draws the same
  verdict for call #n regardless of what else fired around it.
"""

from __future__ import annotations

import hashlib
import random
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Tuple

from tony_tpu import faults

SUITES = ("e2e", "migrate", "fleet", "health")


@dataclass(frozen=True)
class Injection:
    """One (site, spec) pair — the schedule's atom, and the unit the
    shrinker removes."""

    site: str
    spec: str

    def as_dict(self) -> Dict[str, str]:
        return {"site": self.site, "spec": self.spec}


@dataclass
class Schedule:
    seed: int
    index: int
    suite: str
    injections: List[Injection] = field(default_factory=list)

    @property
    def name(self) -> str:
        return f"schedule-{self.index:06d}"

    def rules(self) -> Dict[str, str]:
        """Fold to the injector's rules dict. Duplicate sites compose by
        comma-joining specs (the grammar is comma-combined already)."""
        rules: Dict[str, str] = {}
        for inj in self.injections:
            if inj.site in rules:
                rules[inj.site] = rules[inj.site] + "," + inj.spec
            else:
                rules[inj.site] = inj.spec
        return rules

    def injector(self) -> faults.FaultInjector:
        return faults.FaultInjector(self.rules(),
                                    seed=fault_seed(self.seed, self.index))

    def as_dict(self) -> dict:
        return {"seed": self.seed, "index": self.index,
                "suite": self.suite,
                "injections": [i.as_dict() for i in self.injections]}


def fault_seed(seed: int, index: int) -> int:
    """The injector seed for schedule #index of a sweep: a stable hash,
    NOT seed+index — adjacent sweeps must not share decision streams."""
    h = hashlib.sha256(f"tonychaos:{seed}:{index}".encode()).digest()
    return int.from_bytes(h[:4], "big")


# ---------------------------------------------------------------------------
# Site menus: what can plausibly fire per suite, and how a spec is rolled.
# Each entry is (site, weight, spec_fn(rng) -> spec). Keep every
# generator a pure function of the rng — see the module contract.
# ---------------------------------------------------------------------------
def _spec_first(rng: random.Random) -> str:
    return f"first:{rng.randint(1, 2)}"


def _spec_at(rng: random.Random) -> str:
    return f"at:{rng.randint(1, 8)}"


def _spec_prob(rng: random.Random) -> str:
    return f"prob:{rng.choice(('0.05', '0.1', '0.2'))}"


def _spec_partition(rng: random.Random) -> str:
    direction = rng.choice(("c2s", "s2c"))
    return f"dir:{direction},peer:coordinator,at:{rng.randint(1, 12)}"


def _spec_host_loss(rng: random.Random) -> str:
    # Correlated loss: task:* fires across hosts, so first:N is N
    # near-simultaneous deaths (different hosts, same storm).
    if rng.random() < 0.5:
        return f"task:*,first:{rng.randint(1, 2)}"
    return f"task:*,at:{rng.randint(2, 10)}"


def _spec_disk(rng: random.Random) -> str:
    # Journal appends are frequent; a later index lands mid-run.
    return f"at:{rng.randint(4, 40)}"


def _spec_slow(rng: random.Random) -> str:
    return f"at:{rng.randint(1, 6)},amt:{rng.choice(('0.1', '0.25'))}"


def _spec_flaky(rng: random.Random) -> str:
    # Exactly ONE flaky host per schedule, pinned by name (the daemon
    # fires the site with task_id=<host>): the drill's whole point is
    # that the ledger finds and cordons THIS host.
    host = f"s{rng.randint(0, 1)}h{rng.randint(0, 3)}"
    return f"task:{host},prob:{rng.choice(('0.8', '1.0'))}"


def _spec_probe(rng: random.Random) -> str:
    # Pinned per host like host.flaky; first:N so the host fails its
    # preflight and the grant must self-repair with a spare.
    host = f"s{rng.randint(0, 1)}h{rng.randint(0, 3)}"
    return f"task:{host},first:{rng.randint(1, 2)}"


_Menu = List[Tuple[str, int, Callable[[random.Random], str]]]

#: e2e: a virtual gang runs to self-finish under transport + disk +
#: host-loss pressure.
_E2E_MENU: _Menu = [
    ("rpc.connect", 3, _spec_first),
    ("rpc.send", 3, _spec_at),
    ("rpc.send", 2, _spec_prob),
    ("rpc.partition", 4, _spec_partition),
    ("heartbeat", 2, _spec_prob),
    ("host.loss", 4, _spec_host_loss),
    ("coord.slow-tick", 1, _spec_slow),
    ("disk.full", 2, _spec_disk),
    ("disk.torn", 2, _spec_disk),
]

#: migrate: everything e2e, plus the migration-op sites — the schedule
#: storms a gang that is mid-move.
_MIGRATE_MENU: _Menu = _E2E_MENU + [
    ("migrate.snapshot", 3, _spec_first),
    ("migrate.adopt", 3, _spec_first),
    ("resize.barrier", 2, _spec_first),
    ("resize.remesh", 2, _spec_first),
]

#: fleet: the daemon ticks a multi-tenant pool under grant/preempt
#: storms, slice reclaims, and journal disk faults.
_FLEET_MENU: _Menu = [
    ("fleet.grant", 3, _spec_first),
    ("fleet.preempt", 3, _spec_first),
    ("fleet.ledger", 2, _spec_first),
    ("slice.preempt", 3, _spec_at),
    ("disk.full", 2, _spec_disk),
    ("disk.torn", 2, _spec_disk),
]

#: health: noise AROUND the mandatory flaky host (plan() pins one
#: host.flaky injection unconditionally for this suite) — probe
#: failures force grant self-repair, journal faults stress the
#: write-ahead cordon records.
_HEALTH_MENU: _Menu = [
    ("health.probe", 3, _spec_probe),
    ("fleet.grant", 2, _spec_first),
    ("disk.torn", 1, _spec_disk),
]

_MENUS: Dict[str, _Menu] = {
    "e2e": _E2E_MENU,
    "migrate": _MIGRATE_MENU,
    "fleet": _FLEET_MENU,
    "health": _HEALTH_MENU,
}


def plan(seed: int, index: int, suite: str) -> Schedule:
    """Plan schedule #index of the seed's sweep: 1..4 weighted draws
    from the suite's menu, at most one spec per site (multi-spec sites
    compose at run time via Schedule.rules, but the PLANNER keeps one
    so the shrinker's unit stays meaningful)."""
    if suite not in _MENUS:
        raise ValueError(f"unknown chaos suite {suite!r}; "
                         f"one of {list(_MENUS)}")
    rng = random.Random(f"{seed}:{index}:{suite}")
    menu = _MENUS[suite]
    n = rng.randint(1, 4)
    sites: List[str] = []
    injections: List[Injection] = []
    if suite == "health":
        # The suite's contract: every health schedule seeds exactly one
        # flaky host; the menu draws below only add noise around it.
        sites.append("host.flaky")
        injections.append(Injection("host.flaky", _spec_flaky(rng)))
    weights = [w for _, w, _ in menu]
    for _ in range(n):
        site, _, spec_fn = rng.choices(menu, weights=weights, k=1)[0]
        # Roll the spec even on a duplicate-site skip: the rng stream —
        # hence every LATER draw — must not depend on the skip.
        spec = spec_fn(rng)
        if site in sites:
            continue
        sites.append(site)
        injections.append(Injection(site, spec))
    return Schedule(seed=seed, index=index, suite=suite,
                    injections=injections)
