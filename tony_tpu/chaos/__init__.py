"""tonychaos — the seeded multi-fault chaos engine.

One seed, one reproducible storm. The engine composes the fault-site
registry (tony_tpu/faults.py) into *schedules* — small correlated sets
of injections (host losses, asymmetric RPC partitions, disk faults,
fleet preemption storms) — runs each schedule against the in-process
control plane (a real :class:`Coordinator` over virtual executors, or a
real :class:`FleetDaemon` over a fake job runner), and holds every run
to the invariant ladder:

1. the job SUCCEEDED, or ended terminal with the CORRECT failure
   domain (infra-only injections must never read as USER_ERROR);
2. ``tony-tpu check`` over the run's artifacts is clean;
3. zero orphan processes carry the run's TONY_APP_ID marker;
4. the lock sanitizer and race detector (when armed) stayed quiet.

Every run writes a replayable artifact; ``tony-tpu chaos replay``
re-plans the schedule bit-identically from (seed, index, suite) and
re-runs it, and ``tony-tpu chaos shrink`` delta-debugs a failing
schedule down to the minimal injection set that still fails.

    tony-tpu chaos run --seed 17 --schedules 200 --suite e2e
    tony-tpu chaos replay chaos-artifacts/schedule-000042.json
    tony-tpu chaos shrink chaos-artifacts/schedule-000042.json
"""

from tony_tpu.chaos.artifact import load_artifact, save_artifact
from tony_tpu.chaos.schedule import Injection, Schedule, plan
from tony_tpu.chaos.shrink import ddmin

__all__ = ["Injection", "Schedule", "plan", "ddmin", "load_artifact",
           "save_artifact"]
