"""Execute chaos schedules against the in-process control plane.

Four suites, all subprocess-free so a 200-schedule sweep fits in
minutes, and all REAL control-plane code paths — real RPC frames over
real TCP, real write-ahead journals on real disk, real policy engine:

``e2e``
    One :class:`Coordinator` over a virtual gang (executor/virtual.py):
    4 beat-only tasks self-finish after ``run_s`` through the ordinary
    result path while the schedule storms transport, disk and hosts.
``migrate``
    The e2e substrate, plus a live ``migrate_application`` issued the
    moment the gang establishes — the storm lands on a gang mid-move.
``fleet``
    One :class:`FleetDaemon` over an in-process fake job runner: a
    seeded multi-tenant workload (submits, completions) ticks through
    grant/preempt storms, slice reclaims and journal disk faults.
``health``
    The fleet substrate with ONE seeded flaky host (``host.flaky``
    pinned by name) plus probe/journal noise: the ladder demands the
    failure-attribution ledger quarantines the host, every later grant
    routes around it (journal-proven), fresh jobs still drain, and no
    USER_ERROR ever enters the evidence ledger.

The runner OWNS the global fault injector for the run's duration
(install before, uninstall in finally) and climbs the oracle ladder
afterwards. A schedule that stalls past its deadline is itself a
ladder violation — a chaos storm may fail a job, but it must never
wedge the control plane.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Optional

from tony_tpu import constants, faults
from tony_tpu.chaos import oracle
from tony_tpu.chaos.oracle import Outcome, Violation
from tony_tpu.chaos.schedule import Schedule, fault_seed

log = logging.getLogger(__name__)

#: wall-clock budget per schedule: generous enough for a full retry
#: ladder (seeded backoff), tight enough that a wedged run is a finding.
DEADLINE_S = 90.0


# ---------------------------------------------------------------------------
# e2e / migrate: coordinator over a virtual gang
# ---------------------------------------------------------------------------
def _coord_conf(workers: int = 4, run_s: float = 1.0):
    from tony_tpu.conf import keys as K
    from tony_tpu.conf.config import TonyTpuConfig

    conf = TonyTpuConfig()
    conf.set("tony.worker.instances", workers)
    conf.set("tony.worker.command", "virtual")
    conf.set(K.SCALE_VIRTUAL_EXECUTORS, True)
    conf.set(K.SCALE_VIRTUAL_RUN_S, run_s)
    conf.set(K.TASK_HEARTBEAT_INTERVAL_MS, 150)
    conf.set(K.COORDINATOR_MONITOR_INTERVAL_MS, 50)
    conf.set(K.APPLICATION_NUM_CLIENTS_TO_WAIT, False)
    conf.set(K.DIAGNOSIS_ENABLED, False)
    # Elastic on: host.loss storms shrink-and-continue (the production
    # absorption path) instead of burning a whole epoch per death.
    conf.set(K.ELASTIC_ENABLED, True)
    conf.set(K.ELASTIC_MIN_TASKS, 1)
    conf.set(K.ELASTIC_DRAIN_GRACE_S, 5)
    conf.set(K.ELASTIC_BARRIER_TIMEOUT_S, 20)
    return conf


def _run_coordinator_suite(schedule: Schedule, workdir: str,
                           migrate: bool) -> Outcome:
    from tony_tpu.cluster.local import VirtualExecutorBackend
    from tony_tpu.coordinator.coordinator import Coordinator

    app_id = f"chaos_{schedule.suite}_{schedule.index:06d}"
    conf = _coord_conf()
    backend = VirtualExecutorBackend.from_conf(
        conf, os.path.join(workdir, "work"))
    history = os.path.join(workdir, "history")
    outcome = Outcome()
    crash: list = []

    coord = Coordinator(conf, app_id, backend, history, user="chaos")

    def _run() -> None:
        try:
            coord.run()
        except BaseException as e:  # noqa: BLE001 — a crash IS a finding
            crash.append(e)

    runner = threading.Thread(target=_run, daemon=True,
                              name=f"chaos-coord-{schedule.index}")
    runner.start()
    deadline = time.monotonic() + DEADLINE_S
    try:
        if migrate:
            # Fire the move the moment the gang establishes; if the
            # storm kills establishment first, the migrate is skipped —
            # the schedule still exercised the launch path.
            while time.monotonic() < deadline:
                if coord.session.status.value in ("FAILED", "KILLED",
                                                  "SUCCEEDED"):
                    break
                if coord.elastic.established \
                        and not coord.elastic.resizing:
                    try:
                        coord.migrate_application("slice-1",
                                                  reason="chaos drill")
                    except Exception as e:  # noqa: BLE001
                        log.info("chaos migrate refused: %s", e)
                    break
                time.sleep(0.05)
        while time.monotonic() < deadline:
            if not runner.is_alive():
                break
            time.sleep(0.05)
    finally:
        stalled = runner.is_alive()
        if stalled:
            try:
                coord.request_stop("chaos deadline")
            except Exception:  # noqa: BLE001
                pass
            runner.join(timeout=15)
        if runner.is_alive():
            outcome.violations.append(Violation(
                "verdict", f"run wedged: coordinator still alive "
                           f"{DEADLINE_S:.0f}s past launch and deaf to "
                           f"request_stop"))
            # last-resort teardown so the sweep can continue
            try:
                coord.rpc._server.server_close()
            except Exception:  # noqa: BLE001
                pass
        try:
            backend.stop()
        except Exception:  # noqa: BLE001
            pass

    status = coord.session.status.value
    domain = (coord.session.failure_domain.value
              if coord.session.failure_domain else "")
    outcome.status = status
    outcome.failure_domain = domain
    if crash:
        outcome.detail = f"coordinator crashed: {crash[0]!r}"
        if status not in ("SUCCEEDED", "FAILED", "KILLED"):
            outcome.violations.append(Violation(
                "verdict", f"coordinator thread died on unhandled "
                           f"{crash[0]!r} with the session left "
                           f"{status}"))
    return outcome


# ---------------------------------------------------------------------------
# fleet: daemon over an in-process runner
# ---------------------------------------------------------------------------
class _ChaosHandle:
    def __init__(self, pid: int) -> None:
        self.pid = pid
        self.exit: Optional[int] = None

    def poll(self) -> Optional[int]:
        return self.exit


class _ChaosRunner:
    """SubprocessJobRunner stand-in (the tests' FakeRunner shape): no
    processes, handles exit on command — the chaos workload script
    completes jobs between ticks."""

    def __init__(self) -> None:
        self.handles = {}
        self._next_pid = 40000

    def spawn(self, workdir: str, overrides: dict) -> _ChaosHandle:
        os.makedirs(workdir, exist_ok=True)
        self._next_pid += 1
        h = _ChaosHandle(self._next_pid)
        self.handles[os.path.basename(workdir)] = h
        return h

    def poll(self, handle: _ChaosHandle) -> Optional[int]:
        return handle.poll()

    def resize(self, workdir: str, size: int) -> bool:
        return True

    def migrate(self, workdir: str, target: str) -> bool:
        return True

    def kill(self, workdir: str) -> bool:
        h = self.handles.get(os.path.basename(workdir))
        if h is not None and h.exit is None:
            h.exit = 143
        return True


def _run_fleet_suite(schedule: Schedule, workdir: str) -> Outcome:
    import random

    from tony_tpu.fleet.daemon import FleetDaemon, RUNNING
    from tony_tpu.utils.durable import DurableWriteError

    outcome = Outcome()
    fleet_dir = os.path.join(workdir, "fleet")
    runner = _ChaosRunner()
    daemon = FleetDaemon(fleet_dir, slices=2, hosts_per_slice=4,
                         quotas="", runner=runner, tick_s=0.05)
    # The WORKLOAD is seeded like the faults: same schedule, same
    # submit/complete script, tick for tick.
    rng = random.Random(f"workload:{fault_seed(schedule.seed, schedule.index)}")
    submits = [("tenant-" + str(rng.randint(0, 2)),
                rng.choice((1, 2, 4)), rng.randint(0, 2))
               for _ in range(rng.randint(3, 6))]
    ticks = 40
    journal_dead = False
    try:
        for tick_no in range(ticks):
            if daemon.journal.dead is not None:
                journal_dead = True
                break
            while submits and rng.random() < 0.4:
                tenant, hosts, prio = submits.pop()
                daemon.submit(tenant, hosts, priority=prio,
                              min_hosts=1, conf={})
            try:
                daemon.tick()
            except DurableWriteError:
                journal_dead = True
                break
            except Exception as e:  # noqa: BLE001 — run() survives these
                if daemon.journal.dead is not None:
                    journal_dead = True
                    break
                log.info("chaos fleet tick error (absorbed): %s", e)
            # Complete a running job now and then: churn admits the
            # next queued tenant and exercises release accounting.
            if rng.random() < 0.2:
                with daemon._lock:
                    running = [j for j in daemon.jobs.values()
                               if j.state == RUNNING]
                if running:
                    victim = rng.choice(running)
                    h = runner.handles.get(victim.req.job_id)
                    if h is not None and h.exit is None:
                        h.exit = 0
    finally:
        try:
            daemon._shutdown()
        except Exception:  # noqa: BLE001
            pass

    if journal_dead:
        # The documented degrade: stop loudly, point at --recover.
        outcome.status = "FAILED"
        outcome.failure_domain = "INFRA_TRANSIENT"
        outcome.detail = f"fleet journal dead: {daemon.journal.dead}"
    else:
        outcome.status = "SUCCEEDED"
        # Accounting must balance: pool used == sum of RUNNING grants.
        st = daemon.status()
        booked = sum(j["hosts"] for j in st["jobs"]
                     if j["state"] == RUNNING)
        if st["pool"]["used"] != booked:
            outcome.violations.append(Violation(
                "verdict", f"pool accounting skew: used="
                           f"{st['pool']['used']} but RUNNING grants "
                           f"book {booked}"))
    return outcome


# ---------------------------------------------------------------------------
# health: daemon over an in-process runner with one seeded flaky host
# ---------------------------------------------------------------------------
def _flaky_host_of(schedule: Schedule) -> str:
    for inj in schedule.injections:
        if inj.site == "host.flaky":
            for part in inj.spec.split(","):
                if part.startswith("task:"):
                    return part[len("task:"):]
    return ""


def _run_health_suite(schedule: Schedule, workdir: str) -> Outcome:
    """The flaky-host drill: the schedule pins ``host.flaky`` to one
    host; the ladder demands (a) the ledger quarantines that host, (b)
    every grant journaled after the cordon routes around it, (c) jobs
    submitted after the cordon still drain, and (d) no USER_ERROR ever
    enters the evidence ledger — an infra-only storm must never be
    pinned on the user."""
    import random

    from tony_tpu.fleet import health as fhealth
    from tony_tpu.fleet import journal as fjournal
    from tony_tpu.fleet.daemon import GRANTED, QUEUED, RUNNING, \
        FleetDaemon
    from tony_tpu.utils.durable import DurableWriteError

    outcome = Outcome()
    flaky = _flaky_host_of(schedule)
    fleet_dir = os.path.join(workdir, "fleet")
    runner = _ChaosRunner()
    # Tight thresholds so the drill converges inside the tick budget:
    # two attributed kills quarantine the host; the long half-life and
    # cooldown keep the cordon from decaying or re-admitting mid-run.
    hcfg = fhealth.HealthConfig(half_life_s=3600.0,
                                suspect_threshold=1.0,
                                quarantine_threshold=2.0,
                                quarantine_s=3600.0)
    daemon = FleetDaemon(fleet_dir, slices=2, hosts_per_slice=4,
                         quotas="", runner=runner, tick_s=0.05,
                         health_conf=hcfg)
    rng = random.Random(
        f"workload:{fault_seed(schedule.seed, schedule.index)}")
    # Saturating workload: until the cordon lands, keep enough
    # shrink-to-fit 2-host gangs in flight that EVERY free host (the
    # flaky one included) hosts work each round — attribution becomes
    # a matter of ticks, not placement luck. Small gangs on purpose: a
    # 4-host gang cannot pack once each slice carries a cordon, and
    # the policy's head-of-line hold would then (correctly, but
    # uninterestingly for THIS drill) wedge the queue behind it.
    journal_dead = False

    def _tick() -> bool:
        """One daemon tick; False when the journal died."""
        nonlocal journal_dead
        if daemon.journal.dead is not None:
            journal_dead = True
            return False
        try:
            daemon.tick()
        except DurableWriteError:
            journal_dead = True
            return False
        except Exception as e:  # noqa: BLE001 — run() survives these
            if daemon.journal.dead is not None:
                journal_dead = True
                return False
            log.info("chaos health tick error (absorbed): %s", e)
        return True

    def _complete_some(p: float) -> None:
        with daemon._lock:
            running = [j for j in daemon.jobs.values()
                       if j.state == RUNNING]
        if running and rng.random() < p:
            victim = rng.choice(running)
            hnd = runner.handles.get(victim.req.job_id)
            if hnd is not None and hnd.exit is None:
                hnd.exit = 0

    def _cordoned() -> bool:
        with daemon._lock:
            h = daemon.book.hosts.get(flaky)
            return h is not None and h.state in (
                fhealth.QUARANTINED, fhealth.PROBATION)

    try:
        # Phase 1: saturate until the ledger cordons the flaky host.
        for _ in range(80):
            if _cordoned():
                break
            with daemon._lock:
                alive = sum(1 for j in daemon.jobs.values()
                            if j.state in (QUEUED, GRANTED, RUNNING))
            while alive < 6:
                daemon.submit("tenant-" + str(rng.randint(0, 2)), 2,
                              priority=rng.randint(0, 1), min_hosts=1,
                              conf={})
                alive += 1
            if not _tick():
                break
            _complete_some(0.3)
        # Phase 2: the drain probe — fresh work submitted AFTER the
        # cordon must still grant, minus the bad host. Top priority so
        # it outranks whatever phase 1 left queued.
        if not journal_dead and _cordoned():
            daemon.submit("tenant-drain", 2, priority=3, min_hosts=1,
                          conf={})
            daemon.submit("tenant-drain", 2, priority=3, min_hosts=1,
                          conf={})
            for _ in range(40):
                if not _tick():
                    break
                _complete_some(0.5)
    finally:
        try:
            daemon._shutdown()
        except Exception:  # noqa: BLE001
            pass

    if journal_dead:
        outcome.status = "FAILED"
        outcome.failure_domain = "INFRA_TRANSIENT"
        outcome.detail = f"fleet journal dead: {daemon.journal.dead}"
        return outcome
    outcome.status = "SUCCEEDED"

    # Journal-proven ladder: fold the record stream in order.
    from tony_tpu.devtools.invariants import _iter_journal_records
    recs, _ = _iter_journal_records(
        os.path.join(fleet_dir, constants.FLEET_JOURNAL_FILE))
    cordon_at = None        # record index of the first flaky quarantine
    grants_after = 0
    for idx, rec in recs:
        t = rec.get("t")
        if t == fjournal.REC_FLEET_HEALTH:
            for ev in rec.get("evidence") or []:
                if ev.get("kind") == "USER_ERROR":
                    outcome.violations.append(Violation(
                        "verdict",
                        f"record {idx}: USER_ERROR entered the health "
                        f"evidence ledger for {rec.get('host')} — user "
                        f"bugs must never cordon hardware"))
            if rec.get("host") == flaky \
                    and rec.get("state") == fhealth.QUARANTINED \
                    and cordon_at is None:
                cordon_at = idx
        elif t == fjournal.REC_FLEET_GRANT and cordon_at is not None:
            grants_after += 1
            if flaky in (rec.get("host_ids") or []):
                outcome.violations.append(Violation(
                    "verdict",
                    f"record {idx}: grant of {rec.get('job')} placed "
                    f"on {flaky} AFTER its quarantine at record "
                    f"{cordon_at} — placements must route around a "
                    f"cordoned host"))
    if cordon_at is None:
        outcome.violations.append(Violation(
            "verdict",
            f"seeded flaky host {flaky} was never quarantined — the "
            f"failure-attribution ledger missed the drill's storm"))
    elif grants_after == 0:
        outcome.violations.append(Violation(
            "verdict",
            f"no grant landed after {flaky}'s quarantine at record "
            f"{cordon_at} — the fleet wedged instead of draining "
            f"around the bad host"))
    return outcome


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------
def run_schedule(schedule: Schedule, workdir: str) -> Outcome:
    """Execute one schedule in a fresh workdir and climb the ladder."""
    os.makedirs(workdir, exist_ok=True)
    gates = oracle.snapshot_gates()
    injector = schedule.injector()
    faults.install(injector)
    try:
        if schedule.suite in ("e2e", "migrate"):
            outcome = _run_coordinator_suite(
                schedule, workdir, migrate=(schedule.suite == "migrate"))
        elif schedule.suite == "fleet":
            outcome = _run_fleet_suite(schedule, workdir)
        elif schedule.suite == "health":
            outcome = _run_health_suite(schedule, workdir)
        else:
            raise ValueError(f"unknown chaos suite {schedule.suite!r}")
    finally:
        faults.uninstall()

    oracle.check_verdict(outcome.status, outcome.failure_domain,
                         outcome.violations)
    oracle.check_artifacts(workdir, outcome.violations)
    app_id = f"chaos_{schedule.suite}_{schedule.index:06d}"
    oracle.check_orphans(app_id, outcome.violations,
                         timeout_s=2.0)
    oracle.check_gates(gates, outcome.violations)
    return outcome
