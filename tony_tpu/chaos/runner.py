"""Execute chaos schedules against the in-process control plane.

Three suites, all subprocess-free so a 200-schedule sweep fits in
minutes, and all REAL control-plane code paths — real RPC frames over
real TCP, real write-ahead journals on real disk, real policy engine:

``e2e``
    One :class:`Coordinator` over a virtual gang (executor/virtual.py):
    4 beat-only tasks self-finish after ``run_s`` through the ordinary
    result path while the schedule storms transport, disk and hosts.
``migrate``
    The e2e substrate, plus a live ``migrate_application`` issued the
    moment the gang establishes — the storm lands on a gang mid-move.
``fleet``
    One :class:`FleetDaemon` over an in-process fake job runner: a
    seeded multi-tenant workload (submits, completions) ticks through
    grant/preempt storms, slice reclaims and journal disk faults.

The runner OWNS the global fault injector for the run's duration
(install before, uninstall in finally) and climbs the oracle ladder
afterwards. A schedule that stalls past its deadline is itself a
ladder violation — a chaos storm may fail a job, but it must never
wedge the control plane.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Optional

from tony_tpu import faults
from tony_tpu.chaos import oracle
from tony_tpu.chaos.oracle import Outcome, Violation
from tony_tpu.chaos.schedule import Schedule, fault_seed

log = logging.getLogger(__name__)

#: wall-clock budget per schedule: generous enough for a full retry
#: ladder (seeded backoff), tight enough that a wedged run is a finding.
DEADLINE_S = 90.0


# ---------------------------------------------------------------------------
# e2e / migrate: coordinator over a virtual gang
# ---------------------------------------------------------------------------
def _coord_conf(workers: int = 4, run_s: float = 1.0):
    from tony_tpu.conf import keys as K
    from tony_tpu.conf.config import TonyTpuConfig

    conf = TonyTpuConfig()
    conf.set("tony.worker.instances", workers)
    conf.set("tony.worker.command", "virtual")
    conf.set(K.SCALE_VIRTUAL_EXECUTORS, True)
    conf.set(K.SCALE_VIRTUAL_RUN_S, run_s)
    conf.set(K.TASK_HEARTBEAT_INTERVAL_MS, 150)
    conf.set(K.COORDINATOR_MONITOR_INTERVAL_MS, 50)
    conf.set(K.APPLICATION_NUM_CLIENTS_TO_WAIT, False)
    conf.set(K.DIAGNOSIS_ENABLED, False)
    # Elastic on: host.loss storms shrink-and-continue (the production
    # absorption path) instead of burning a whole epoch per death.
    conf.set(K.ELASTIC_ENABLED, True)
    conf.set(K.ELASTIC_MIN_TASKS, 1)
    conf.set(K.ELASTIC_DRAIN_GRACE_S, 5)
    conf.set(K.ELASTIC_BARRIER_TIMEOUT_S, 20)
    return conf


def _run_coordinator_suite(schedule: Schedule, workdir: str,
                           migrate: bool) -> Outcome:
    from tony_tpu.cluster.local import VirtualExecutorBackend
    from tony_tpu.coordinator.coordinator import Coordinator

    app_id = f"chaos_{schedule.suite}_{schedule.index:06d}"
    conf = _coord_conf()
    backend = VirtualExecutorBackend.from_conf(
        conf, os.path.join(workdir, "work"))
    history = os.path.join(workdir, "history")
    outcome = Outcome()
    crash: list = []

    coord = Coordinator(conf, app_id, backend, history, user="chaos")

    def _run() -> None:
        try:
            coord.run()
        except BaseException as e:  # noqa: BLE001 — a crash IS a finding
            crash.append(e)

    runner = threading.Thread(target=_run, daemon=True,
                              name=f"chaos-coord-{schedule.index}")
    runner.start()
    deadline = time.monotonic() + DEADLINE_S
    try:
        if migrate:
            # Fire the move the moment the gang establishes; if the
            # storm kills establishment first, the migrate is skipped —
            # the schedule still exercised the launch path.
            while time.monotonic() < deadline:
                if coord.session.status.value in ("FAILED", "KILLED",
                                                  "SUCCEEDED"):
                    break
                if coord.elastic.established \
                        and not coord.elastic.resizing:
                    try:
                        coord.migrate_application("slice-1",
                                                  reason="chaos drill")
                    except Exception as e:  # noqa: BLE001
                        log.info("chaos migrate refused: %s", e)
                    break
                time.sleep(0.05)
        while time.monotonic() < deadline:
            if not runner.is_alive():
                break
            time.sleep(0.05)
    finally:
        stalled = runner.is_alive()
        if stalled:
            try:
                coord.request_stop("chaos deadline")
            except Exception:  # noqa: BLE001
                pass
            runner.join(timeout=15)
        if runner.is_alive():
            outcome.violations.append(Violation(
                "verdict", f"run wedged: coordinator still alive "
                           f"{DEADLINE_S:.0f}s past launch and deaf to "
                           f"request_stop"))
            # last-resort teardown so the sweep can continue
            try:
                coord.rpc._server.server_close()
            except Exception:  # noqa: BLE001
                pass
        try:
            backend.stop()
        except Exception:  # noqa: BLE001
            pass

    status = coord.session.status.value
    domain = (coord.session.failure_domain.value
              if coord.session.failure_domain else "")
    outcome.status = status
    outcome.failure_domain = domain
    if crash:
        outcome.detail = f"coordinator crashed: {crash[0]!r}"
        if status not in ("SUCCEEDED", "FAILED", "KILLED"):
            outcome.violations.append(Violation(
                "verdict", f"coordinator thread died on unhandled "
                           f"{crash[0]!r} with the session left "
                           f"{status}"))
    return outcome


# ---------------------------------------------------------------------------
# fleet: daemon over an in-process runner
# ---------------------------------------------------------------------------
class _ChaosHandle:
    def __init__(self, pid: int) -> None:
        self.pid = pid
        self.exit: Optional[int] = None

    def poll(self) -> Optional[int]:
        return self.exit


class _ChaosRunner:
    """SubprocessJobRunner stand-in (the tests' FakeRunner shape): no
    processes, handles exit on command — the chaos workload script
    completes jobs between ticks."""

    def __init__(self) -> None:
        self.handles = {}
        self._next_pid = 40000

    def spawn(self, workdir: str, overrides: dict) -> _ChaosHandle:
        os.makedirs(workdir, exist_ok=True)
        self._next_pid += 1
        h = _ChaosHandle(self._next_pid)
        self.handles[os.path.basename(workdir)] = h
        return h

    def poll(self, handle: _ChaosHandle) -> Optional[int]:
        return handle.poll()

    def resize(self, workdir: str, size: int) -> bool:
        return True

    def migrate(self, workdir: str, target: str) -> bool:
        return True

    def kill(self, workdir: str) -> bool:
        h = self.handles.get(os.path.basename(workdir))
        if h is not None and h.exit is None:
            h.exit = 143
        return True


def _run_fleet_suite(schedule: Schedule, workdir: str) -> Outcome:
    import random

    from tony_tpu.fleet.daemon import FleetDaemon, RUNNING
    from tony_tpu.utils.durable import DurableWriteError

    outcome = Outcome()
    fleet_dir = os.path.join(workdir, "fleet")
    runner = _ChaosRunner()
    daemon = FleetDaemon(fleet_dir, slices=2, hosts_per_slice=4,
                         quotas="", runner=runner, tick_s=0.05)
    # The WORKLOAD is seeded like the faults: same schedule, same
    # submit/complete script, tick for tick.
    rng = random.Random(f"workload:{fault_seed(schedule.seed, schedule.index)}")
    submits = [("tenant-" + str(rng.randint(0, 2)),
                rng.choice((1, 2, 4)), rng.randint(0, 2))
               for _ in range(rng.randint(3, 6))]
    ticks = 40
    journal_dead = False
    try:
        for tick_no in range(ticks):
            if daemon.journal.dead is not None:
                journal_dead = True
                break
            while submits and rng.random() < 0.4:
                tenant, hosts, prio = submits.pop()
                daemon.submit(tenant, hosts, priority=prio,
                              min_hosts=1, conf={})
            try:
                daemon.tick()
            except DurableWriteError:
                journal_dead = True
                break
            except Exception as e:  # noqa: BLE001 — run() survives these
                if daemon.journal.dead is not None:
                    journal_dead = True
                    break
                log.info("chaos fleet tick error (absorbed): %s", e)
            # Complete a running job now and then: churn admits the
            # next queued tenant and exercises release accounting.
            if rng.random() < 0.2:
                with daemon._lock:
                    running = [j for j in daemon.jobs.values()
                               if j.state == RUNNING]
                if running:
                    victim = rng.choice(running)
                    h = runner.handles.get(victim.req.job_id)
                    if h is not None and h.exit is None:
                        h.exit = 0
    finally:
        try:
            daemon._shutdown()
        except Exception:  # noqa: BLE001
            pass

    if journal_dead:
        # The documented degrade: stop loudly, point at --recover.
        outcome.status = "FAILED"
        outcome.failure_domain = "INFRA_TRANSIENT"
        outcome.detail = f"fleet journal dead: {daemon.journal.dead}"
    else:
        outcome.status = "SUCCEEDED"
        # Accounting must balance: pool used == sum of RUNNING grants.
        st = daemon.status()
        booked = sum(j["hosts"] for j in st["jobs"]
                     if j["state"] == RUNNING)
        if st["pool"]["used"] != booked:
            outcome.violations.append(Violation(
                "verdict", f"pool accounting skew: used="
                           f"{st['pool']['used']} but RUNNING grants "
                           f"book {booked}"))
    return outcome


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------
def run_schedule(schedule: Schedule, workdir: str) -> Outcome:
    """Execute one schedule in a fresh workdir and climb the ladder."""
    os.makedirs(workdir, exist_ok=True)
    gates = oracle.snapshot_gates()
    injector = schedule.injector()
    faults.install(injector)
    try:
        if schedule.suite in ("e2e", "migrate"):
            outcome = _run_coordinator_suite(
                schedule, workdir, migrate=(schedule.suite == "migrate"))
        elif schedule.suite == "fleet":
            outcome = _run_fleet_suite(schedule, workdir)
        else:
            raise ValueError(f"unknown chaos suite {schedule.suite!r}")
    finally:
        faults.uninstall()

    oracle.check_verdict(outcome.status, outcome.failure_domain,
                         outcome.violations)
    oracle.check_artifacts(workdir, outcome.violations)
    app_id = f"chaos_{schedule.suite}_{schedule.index:06d}"
    oracle.check_orphans(app_id, outcome.violations,
                         timeout_s=2.0)
    oracle.check_gates(gates, outcome.violations)
    return outcome
