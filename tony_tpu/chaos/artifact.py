"""Replayable run artifacts: one JSON file per executed schedule.

The artifact is the repro: it carries the (seed, index, suite) triple
the planner needs to regenerate the schedule bit-identically, the
planned injections (so `replay` can PROVE the regeneration matched
before trusting it), and the run's outcome + ladder violations. A
shrunk artifact additionally records the surviving injection subset
under ``shrunk_from`` provenance — the seed corpus checks these in.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from tony_tpu.chaos.oracle import Outcome, Violation
from tony_tpu.chaos.schedule import Injection, Schedule
from tony_tpu.utils.durable import atomic_write

VERSION = 1


def artifact_path(outdir: str, schedule: Schedule) -> str:
    return os.path.join(outdir, f"{schedule.name}.json")


def save_artifact(outdir: str, schedule: Schedule, outcome: Outcome,
                  shrunk_from: Optional[dict] = None,
                  note: str = "") -> str:
    os.makedirs(outdir, exist_ok=True)
    doc = {
        "version": VERSION,
        "schedule": schedule.as_dict(),
        "outcome": outcome.as_dict(),
    }
    if shrunk_from:
        doc["shrunk_from"] = shrunk_from
    if note:
        doc["note"] = note
    path = artifact_path(outdir, schedule)
    atomic_write(path,
                 (json.dumps(doc, indent=2, sort_keys=True) + "\n").encode())
    return path


def load_artifact(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("version") != VERSION:
        raise ValueError(f"unsupported chaos artifact version "
                         f"{doc.get('version')!r} in {path}")
    sched = doc.get("schedule") or {}
    for key in ("seed", "index", "suite"):
        if key not in sched:
            raise ValueError(f"chaos artifact {path} missing "
                             f"schedule.{key}")
    return doc


def schedule_from_doc(doc: dict) -> Schedule:
    """The schedule AS RECORDED (shrunk artifacts carry a subset the
    planner would never emit — replay must honour what actually ran)."""
    sched = doc["schedule"]
    return Schedule(
        seed=int(sched["seed"]), index=int(sched["index"]),
        suite=str(sched["suite"]),
        injections=[Injection(i["site"], i["spec"])
                    for i in sched.get("injections", [])])


def outcome_from_doc(doc: dict) -> Outcome:
    rec = doc.get("outcome") or {}
    out = Outcome(status=str(rec.get("status", "")),
                  failure_domain=str(rec.get("failure_domain", "")),
                  detail=str(rec.get("detail", "")))
    for v in rec.get("violations", []):
        out.violations.append(Violation(str(v.get("rung", "?")),
                                        str(v.get("detail", ""))))
    return out
