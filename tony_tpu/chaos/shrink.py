"""Delta-debugging shrinker: failing schedule -> minimal repro.

Classic ddmin (Zeller) over the schedule's injection list. The
predicate re-RUNS the candidate subset through the real runner; thanks
to ``prob:P``'s stable per-call hash and the scope-only dir/peer/task
filters, removing one injection does not re-roll the survivors'
decisions — the search space behaves, and the minimal set it converges
on is a real repro, not an artifact of RNG drift.

The result is 1-minimal: removing ANY single surviving injection makes
the failure disappear. That is the strongest claim a black-box shrink
can make, and exactly what a debugging session wants pinned in the
seed corpus.
"""

from __future__ import annotations

import logging
from typing import Callable, List, Sequence, TypeVar

log = logging.getLogger(__name__)

T = TypeVar("T")


def ddmin(items: Sequence[T], fails: Callable[[List[T]], bool],
          max_runs: int = 200) -> List[T]:
    """Minimize ``items`` such that ``fails(result)`` still holds.

    ``fails`` must hold for the full input (the caller verifies; we
    assert). Returns a 1-minimal failing subset. ``max_runs`` bounds
    predicate invocations — on exhaustion the best-so-far subset is
    returned (still failing, possibly not yet 1-minimal).
    """
    current = list(items)
    if not fails(current):
        raise ValueError("ddmin needs a failing input to shrink")
    runs = 1
    granularity = 2
    while len(current) >= 2:
        chunk = max(1, len(current) // granularity)
        starts = list(range(0, len(current), chunk))
        subsets = [current[i:i + chunk] for i in starts]
        complements = [current[:i] + current[i + chunk:] for i in starts]
        reduced = False
        # Try each subset alone, then each complement.
        for candidate in subsets + complements:
            if not candidate or len(candidate) == len(current):
                continue
            if runs >= max_runs:
                log.warning("ddmin budget exhausted after %d runs at "
                            "%d item(s)", runs, len(current))
                return current
            runs += 1
            if fails(list(candidate)):
                current = list(candidate)
                granularity = max(granularity - 1, 2)
                reduced = True
                break
        if not reduced:
            if granularity >= len(current):
                break
            granularity = min(granularity * 2, len(current))
    log.info("ddmin: %d -> %d item(s) in %d run(s)",
             len(items), len(current), runs)
    return current
