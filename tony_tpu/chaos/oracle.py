"""The invariant ladder every chaos run must climb.

A schedule "passes" when all four rungs hold; each violated rung is a
recorded, replayable finding, not an exception — the runner keeps
sweeping and the artifact carries the violation list.

1. **Verdict** — the run ended SUCCEEDED, or terminal with a failure
   domain the injections can legitimately cause. Every chaos injection
   is infrastructure (transport, disk, host, scheduler), so a terminal
   USER_ERROR is ALWAYS a ladder violation: it means an injected infra
   fault was mis-attributed to the user's code.
2. **Artifacts** — ``tony-tpu check`` (devtools/invariants.py) over the
   run's tree is clean: journals replayable, write-ahead brackets
   paired, no half-applied topology on disk.
3. **Orphans** — no live process carries the run's ``TONY_APP_ID``
   environment marker (mirrors tests/procwatch.py; the chaos CLI cannot
   import the test tree).
4. **Gates** — the lock sanitizer and race detector, when armed, report
   nothing new for the run's duration.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

#: failure domains an infra-only storm may legitimately produce
ALLOWED_TERMINAL_DOMAINS = ("INFRA_TRANSIENT", "PREEMPTION")


@dataclass
class Violation:
    rung: str           # verdict | artifacts | orphans | gates
    detail: str

    def as_dict(self) -> dict:
        return {"rung": self.rung, "detail": self.detail}


@dataclass
class GateSnapshot:
    """Sanitizer/race counters BEFORE the run; the post-run check
    reports only what the run itself added."""

    hazards: int = 0
    races: int = 0


@dataclass
class Outcome:
    status: str = ""                      # SUCCEEDED | FAILED | KILLED
    failure_domain: str = ""
    detail: str = ""
    violations: List[Violation] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations

    def as_dict(self) -> dict:
        return {"status": self.status,
                "failure_domain": self.failure_domain,
                "detail": self.detail,
                "ok": self.ok,
                "violations": [v.as_dict() for v in self.violations]}


def snapshot_gates() -> GateSnapshot:
    snap = GateSnapshot()
    try:
        from tony_tpu.devtools import race, sanitizer
        if sanitizer.enabled():
            snap.hazards = len(sanitizer.state().hazards)
        if race.enabled():
            snap.races = len(race.state().races)
    except Exception:  # noqa: BLE001 — the gates are optional equipment
        pass
    return snap


def check_verdict(status: str, failure_domain: str,
                  violations: List[Violation]) -> None:
    if status == "SUCCEEDED":
        return
    if status in ("FAILED", "KILLED"):
        if failure_domain in ALLOWED_TERMINAL_DOMAINS:
            return
        violations.append(Violation(
            "verdict",
            f"terminal {status} attributed to "
            f"{failure_domain or '<none>'} — an infra-only storm may "
            f"only end in {ALLOWED_TERMINAL_DOMAINS}"))
        return
    violations.append(Violation(
        "verdict", f"run ended non-terminal in state {status!r}"))


def check_artifacts(root: str, violations: List[Violation]) -> None:
    from tony_tpu.devtools import invariants

    try:
        reports = invariants.check_tree(root)
    except Exception as e:  # noqa: BLE001 — a crashed checker IS a finding
        violations.append(Violation("artifacts", f"checker crashed: {e}"))
        return
    for rep in reports:
        if not rep.ok:
            violations.append(Violation(
                "artifacts", invariants.render_text([rep]).strip()))


def _live_pids_with_env(needle: str) -> List[Tuple[int, str]]:
    """(pid, cmdline) of live processes whose environment carries
    ``needle``. Skips self and unreadable entries. (Mirror of
    tests/procwatch.py — the package cannot import the test tree.)"""
    needle_b = needle.encode()
    me = os.getpid()
    out: List[Tuple[int, str]] = []
    try:
        entries = os.listdir("/proc")
    except OSError:
        return out
    for entry in entries:
        if not entry.isdigit() or int(entry) == me:
            continue
        try:
            with open(f"/proc/{entry}/environ", "rb") as f:
                env = f.read()
            if needle_b not in env:
                continue
            with open(f"/proc/{entry}/cmdline", "rb") as f:
                cmd = f.read().replace(b"\0", b" ").decode(
                    "utf-8", "replace").strip()
        except OSError:
            continue
        out.append((int(entry), cmd))
    return out


def check_orphans(app_id: str, violations: List[Violation],
                  timeout_s: float = 5.0) -> None:
    needle = f"TONY_APP_ID={app_id}"
    deadline = time.monotonic() + timeout_s
    survivors = _live_pids_with_env(needle)
    while survivors and time.monotonic() < deadline:
        time.sleep(0.2)
        survivors = _live_pids_with_env(needle)
    for pid, cmd in survivors:
        violations.append(Violation(
            "orphans", f"pid {pid} survived teardown with {needle}: "
                       f"{cmd}"))


def check_gates(before: Optional[GateSnapshot],
                violations: List[Violation]) -> None:
    if before is None:
        return
    try:
        from tony_tpu.devtools import race, sanitizer
    except Exception:  # noqa: BLE001
        return
    try:
        if sanitizer.enabled():
            new = sanitizer.state().hazards[before.hazards:]
            for h in new:
                violations.append(Violation(
                    "gates", f"lock hazard: {h.get('kind', '?')} at "
                             f"{h.get('site', '?')}"))
        if race.enabled():
            new_races = race.state().races[before.races:]
            for r in new_races:
                violations.append(Violation(
                    "gates", f"data race on {r.get('field', '?')} at "
                             f"{r.get('site', '?')}"))
    except Exception:  # noqa: BLE001
        pass
