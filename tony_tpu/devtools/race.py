"""tonyrace — lockset + happens-before data-race detection for the control plane.

The fleet daemon alone runs a poll tick, RPC handler threads, a ledger
fold and a single-flight prom worker over one shared state bag, and the
coordinator mixes its monitor tick with RPC dispatch. PR 7's sanitizer
checks lock *ordering* and hold-while-blocking — never whether a shared
field is actually accessed under a consistent lock. The reference leaned
on Java's ``synchronized``/JMM discipline for exactly this state
(heartbeat maps, session matrix); this module is the Python rewrite's
equivalent enforcement, two-sided:

**Dynamic side** (Eraser-style lockset analysis + a vector-clock
happens-before graph, the hybrid the ThreadSanitizer family converged
on). Classes opt in with the :func:`guarded` decorator and a
``GUARDED_BY`` registry in the class body::

    @guarded
    class FleetDaemon:
        #: field → the lock attribute that must guard it (None = the
        #: field is atomic/single-writer by design and only audited)
        GUARDED_BY = {"jobs": "_lock", "_ledgers": "_lock",
                      "_started": None}

Under ``TONY_RACE_DETECTOR=1`` (checked at ``import tony_tpu`` so every
subprocess of an armed run joins), attribute reads and writes of the
lock-named fields are instrumented: each access records the calling
thread's **lockset** (the sanitizer's wrapped Lock/RLock bookkeeping —
``devtools/sanitizer.py`` owns which locks are held) and its **vector
clock**. Two accesses to the same field race when they come from
different threads, at least one is a write (a *read* of a mutable
container counts as a write: ``self.jobs[k]`` mutates through an
attribute load), their locksets do not intersect, and neither access
happens-before the other. Happens-before edges come from lock
release→acquire, ``Thread.start``/``join``, ``queue.Queue`` put→get and
``Event``/``Condition`` handoffs — so single-flight handoffs (the
coordinator's prom-export worker, the event-writer queue) do not
false-positive. Reports carry both access sites and are dumped
per-process into ``$TONY_RACE_DETECTOR_DIR`` at exit; the tier-1
conftest fails the session on any finding, exactly like the lock
sanitizer. With the env flag off, :func:`guarded` returns the class
untouched — zero overhead.

**Static side** — the ``guarded-by`` tonylint rule family (run via the
ordinary ``tony-tpu lint`` surfaces, suppressed with the usual
``# tony: lint-ignore[...]`` grammar), scoped to ``coordinator/`` and
``fleet/``:

===================  ====================================================
guarded-by           every access to a field declared with a lock in
                     ``GUARDED_BY`` (dict form, or a trailing
                     ``# guarded-by: <lock-attr>`` comment on the
                     ``__init__`` assignment) happens lexically inside
                     ``with self.<lock-attr>:`` — except in ``__init__``
                     (no threads yet) and in ``*_locked`` helpers (the
                     caller-holds-the-lock convention)
guarded-decl         the other direction: on a class that HAS a registry,
                     a ``self.<field> = ...`` store outside ``__init__``
                     to an UNDECLARED field is a violation — shared
                     mutable state must not escape the audit
===================  ====================================================

Unit tests build an isolated :class:`RaceState` (paired with an isolated
sanitizer ``State``) and instrument fixture classes through
:func:`instrument_class` — no global patching, no cross-test bleed.
"""

from __future__ import annotations

import ast
import atexit
import json
import os
import re
import sys
import threading
import weakref
from collections import deque
from typing import (Any, Callable, Dict, FrozenSet, List, Optional, Set,
                    Tuple, Type)

from tony_tpu.devtools import sanitizer

ENV_FLAG = "TONY_RACE_DETECTOR"
ENV_DIR = "TONY_RACE_DETECTOR_DIR"

#: the class-body registry attribute the decorator and the lint read
GUARDED_ATTR = "GUARDED_BY"

#: cap stored races so a pathological loop cannot eat the heap
_MAX_RACES = 100
#: cap per-field read records (threads seen since the last write)
_MAX_READS = 32

#: reads of these types mutate state through the attribute load
#: (``self.jobs[k] = v`` is an attr *read* of ``jobs`` at runtime), so
#: they participate as writes in the race check.
_MUTABLE = (dict, list, set, deque)

#: the per-instance slot holding field access state (never tracked)
_FIELDS_SLOT = "_tony_race_fields_"

_VC = Dict[int, int]
#: one access record: (tid, clock, lockset ids, lock sites, site, thread)
_Rec = Tuple[int, int, FrozenSet[int], Tuple[str, ...], str, str]


def _merge(dst: _VC, src: _VC) -> None:
    for k, v in src.items():
        if dst.get(k, 0) < v:
            dst[k] = v


def _site(extra_skip: int = 0) -> str:
    """Short access site: up to 3 tony frames, innermost first, skipping
    this module and the sanitizer."""
    try:
        f: Any = sys._getframe(2 + extra_skip)
    except ValueError:
        return "?"
    out: List[str] = []
    while f is not None and len(out) < 3:
        fn = f.f_code.co_filename
        if not (fn.endswith(os.path.join("devtools", "race.py"))
                or fn.endswith(os.path.join("devtools", "sanitizer.py"))):
            idx = fn.rfind("tony_tpu")
            short = fn[idx:] if idx >= 0 else os.path.basename(fn)
            out.append(f"{short}:{f.f_lineno} ({f.f_code.co_name})")
        f = f.f_back
    return " < ".join(out) if out else "?"


class RaceState:
    """All detector bookkeeping. The module keeps one global instance
    (paired with the sanitizer's global State for locksets); tests build
    their own pair for isolation."""

    def __init__(self, san: Optional[sanitizer.State] = None) -> None:
        # Raw primitive on purpose: the detector must never instrument
        # its own internals (same rule as the sanitizer).
        self._mu = sanitizer.raw_lock()
        self.san = san if san is not None else sanitizer.state()
        self._tls = threading.local()
        self._next_tid = 0
        #: per-thread vector clocks (alive via the Thread object — a
        #: joiner reads the child's final clock after ``join``)
        self._vcs: "weakref.WeakKeyDictionary[threading.Thread, _VC]" = \
            weakref.WeakKeyDictionary()
        #: creator-snapshot seeds installed by Thread.start
        self._seeds: "weakref.WeakKeyDictionary[threading.Thread, _VC]" = \
            weakref.WeakKeyDictionary()
        #: channel clocks: locks (release→acquire), queues (put→get),
        #: events/conditions (set/notify→wait) all use the same edge
        self._chan: "weakref.WeakKeyDictionary[Any, _VC]" = \
            weakref.WeakKeyDictionary()
        self.races: List[Dict[str, Any]] = []
        self._race_keys: Set[Tuple[str, str, str]] = set()
        self.fields_tracked = 0

    # -- thread identity / clocks ----------------------------------------
    def _ctx(self) -> Tuple[int, _VC]:
        tid = getattr(self._tls, "tid", None)
        if tid is not None:
            return tid, self._tls.vc  # type: ignore[no-any-return]
        th = threading.current_thread()
        with self._mu:
            self._next_tid += 1
            tid = self._next_tid
            vc: _VC = {}
            seed = self._seeds.pop(th, None)
            if seed is not None:
                _merge(vc, seed)
            vc[tid] = 1
            self._vcs[th] = vc
        self._tls.tid = tid
        self._tls.vc = vc
        return tid, vc

    # -- happens-before edges --------------------------------------------
    def send(self, obj: Any) -> None:
        """Publish: the current thread's clock joins ``obj``'s channel
        (lock release, queue put, Event.set, Condition.notify)."""
        tid, vc = self._ctx()
        with self._mu:
            ch = self._chan.get(obj)
            if ch is None:
                ch = {}
                try:
                    self._chan[obj] = ch
                except TypeError:
                    return          # unweakrefable channel: no edge
            _merge(ch, vc)
            vc[tid] = vc[tid] + 1

    def recv(self, obj: Any) -> None:
        """Receive: ``obj``'s channel clock joins the current thread
        (lock acquire, queue get, Event.wait, Condition.wait)."""
        tid, vc = self._ctx()
        with self._mu:
            ch = self._chan.get(obj)
            if ch:
                _merge(vc, ch)

    def note_start(self, thread: threading.Thread) -> None:
        """Thread.start edge: the child begins with everything the
        creator did so far."""
        tid, vc = self._ctx()
        with self._mu:
            try:
                self._seeds[thread] = dict(vc)
            except TypeError:
                return
            vc[tid] = vc[tid] + 1

    def note_join(self, thread: threading.Thread) -> None:
        """Thread.join edge: the joiner sees everything the (finished)
        child did."""
        _, vc = self._ctx()
        with self._mu:
            child = self._vcs.get(thread)
            if child is None:
                child = self._seeds.get(thread)
            if child:
                _merge(vc, child)

    # -- the access check -------------------------------------------------
    def _lockset(self) -> Tuple[FrozenSet[int], Tuple[str, ...]]:
        held = self.san.held_locks()
        if not held:
            return frozenset(), ()
        return (frozenset(id(lk) for lk in held),
                tuple(getattr(lk, "site", "?") for lk in held))

    def note_access(self, obj: Any, cls_name: str, attr: str,
                    guard: str, is_write: bool) -> None:
        d = object.__getattribute__(obj, "__dict__")
        fields = d.get(_FIELDS_SLOT)
        if fields is None:
            fields = d[_FIELDS_SLOT] = {}
        tid, vc = self._ctx()
        clock = vc[tid]
        ls, sites = self._lockset()
        fs = fields.get(attr)
        key = (tid, clock, ls, is_write)
        if fs is not None and fs.get("last") == key:
            return              # same thread, same epoch, same lockset
        with self._mu:
            if fs is None:
                fs = fields[attr] = {"w": None, "r": {}, "last": None}
                self.fields_tracked += 1
            rec: _Rec = (tid, clock, ls, sites, _site(),
                         threading.current_thread().name)
            w = fs["w"]
            if (w is not None and w[0] != tid
                    and w[1] > vc.get(w[0], 0) and not (w[2] & ls)):
                self._report(cls_name, attr, guard,
                             "write-write" if is_write else "write-read",
                             w, rec)
            if is_write:
                for rtid, r in list(fs["r"].items()):
                    if (rtid != tid and r[1] > vc.get(rtid, 0)
                            and not (r[2] & ls)):
                        self._report(cls_name, attr, guard,
                                     "read-write", r, rec)
                fs["w"] = rec
                fs["r"].clear()
            else:
                if len(fs["r"]) < _MAX_READS or tid in fs["r"]:
                    fs["r"][tid] = rec
            fs["last"] = key

    def _report(self, cls_name: str, attr: str, guard: str, kind: str,
                a: _Rec, b: _Rec) -> None:
        key = (cls_name, attr, kind)
        if key in self._race_keys or len(self.races) >= _MAX_RACES:
            return
        self._race_keys.add(key)

        def _acc(r: _Rec) -> Dict[str, Any]:
            return {"thread": r[5], "site": r[4], "locks": list(r[3])}

        self.races.append({
            "class": cls_name, "field": attr, "guard": guard,
            "kind": kind, "a": _acc(a), "b": _acc(b)})

    # -- reporting --------------------------------------------------------
    def report(self) -> Dict[str, Any]:
        with self._mu:
            return {"pid": os.getpid(), "races": list(self.races),
                    "fields_tracked": self.fields_tracked}

    def clear(self) -> None:
        with self._mu:
            self.races.clear()
            self._race_keys.clear()


# ---------------------------------------------------------------------------
# Class instrumentation
# ---------------------------------------------------------------------------
_COMMENT_GUARD_RE = re.compile(
    r"self\.(\w+)\s*(?::[^=]+)?=[^#\n]*#\s*guarded-by:\s*([A-Za-z_]\w*|none)")


def declared_guards(cls: type) -> Dict[str, Optional[str]]:
    """The class's guard registry: the ``GUARDED_BY`` dict merged with
    trailing ``# guarded-by: <lock-attr>`` comments on ``self.x = ...``
    assignments in the class source (``none`` declares an audited-but-
    unguarded field)."""
    out: Dict[str, Optional[str]] = {}
    reg = getattr(cls, GUARDED_ATTR, None)
    if isinstance(reg, dict):
        for k, v in reg.items():
            out[str(k)] = str(v) if v else None
    try:
        import inspect

        src = inspect.getsource(cls)
    except (OSError, TypeError):
        return out
    for m in _COMMENT_GUARD_RE.finditer(src):
        field, guard = m.group(1), m.group(2)
        out.setdefault(field, None if guard == "none" else guard)
    return out


def instrument_class(cls: Type[Any],
                     state: Optional[RaceState] = None) -> Type[Any]:
    """Wrap ``cls``'s attribute access so lock-declared ``GUARDED_BY``
    fields feed ``state`` (default: the global detector). Unconditional —
    the :func:`guarded` decorator is the enablement-gated entry point;
    tests call this directly with an isolated state."""
    tracked: Dict[str, str] = {
        f: g for f, g in declared_guards(cls).items() if g}
    if not tracked:
        return cls
    tracked_set = frozenset(tracked)
    cls_name = cls.__name__
    get_state: Callable[[], RaceState]
    if state is None:
        get_state = _global_state
    else:
        def get_state(_s: RaceState = state) -> RaceState:
            return _s
    orig_get = cls.__getattribute__
    orig_set = cls.__setattr__

    def __getattribute__(self: Any, name: str) -> Any:
        value = orig_get(self, name)
        if name in tracked_set:
            get_state().note_access(
                self, cls_name, name, tracked[name],
                isinstance(value, _MUTABLE))
        return value

    def __setattr__(self: Any, name: str, value: Any) -> None:
        if name in tracked_set:
            get_state().note_access(self, cls_name, name, tracked[name],
                                    True)
        orig_set(self, name, value)

    cls.__getattribute__ = __getattribute__  # type: ignore[method-assign]
    cls.__setattr__ = __setattr__            # type: ignore[method-assign]
    return cls


def guarded(cls: Type[Any]) -> Type[Any]:
    """Class decorator: arm the declared ``GUARDED_BY`` fields for race
    detection when ``TONY_RACE_DETECTOR=1``; the class comes back
    untouched (same object, same methods) when the detector is off."""
    if not _enabled:
        return cls
    return instrument_class(cls)


# ---------------------------------------------------------------------------
# Global enablement
# ---------------------------------------------------------------------------
_state: Optional[RaceState] = None
_enabled = False
_real: Dict[str, Any] = {}


def _global_state() -> RaceState:
    global _state
    if _state is None:
        _state = RaceState()
    return _state


def state() -> RaceState:
    return _global_state()


def enabled() -> bool:
    return _enabled


def enable() -> None:
    """Arm the detector: requires (and enables) the lock sanitizer for
    locksets, registers this state for lock-edge callbacks, and patches
    the thread/queue handoff primitives for HB edges. Idempotent."""
    global _enabled
    if _enabled:
        return
    _enabled = True
    sanitizer.enable()
    st = _global_state()
    st.san = sanitizer.state()
    sanitizer.set_race_listener(st)
    import queue

    _real["thread_start"] = threading.Thread.start
    _real["thread_join"] = threading.Thread.join
    _real["queue_put"] = queue.Queue.put
    _real["queue_get"] = queue.Queue.get

    def _start(self: threading.Thread) -> None:
        _global_state().note_start(self)
        _real["thread_start"](self)

    def _join(self: threading.Thread,
              timeout: Optional[float] = None) -> None:
        _real["thread_join"](self, timeout)
        if not self.is_alive():
            _global_state().note_join(self)

    def _put(self: Any, item: Any, block: bool = True,
             timeout: Optional[float] = None) -> None:
        _global_state().send(self)
        _real["queue_put"](self, item, block, timeout)

    def _get(self: Any, block: bool = True,
             timeout: Optional[float] = None) -> Any:
        item = _real["queue_get"](self, block, timeout)
        _global_state().recv(self)
        return item

    threading.Thread.start = _start          # type: ignore[method-assign]
    threading.Thread.join = _join            # type: ignore[method-assign]
    queue.Queue.put = _put                   # type: ignore[method-assign]
    queue.Queue.get = _get                   # type: ignore[method-assign]
    atexit.register(_dump_at_exit)


def disable() -> None:
    """Restore the real primitives. Classes already instrumented stay
    instrumented (their accesses keep feeding the state) — same contract
    as the sanitizer's disable()."""
    global _enabled
    if not _enabled:
        return
    _enabled = False
    import queue

    threading.Thread.start = _real["thread_start"]
    threading.Thread.join = _real["thread_join"]
    queue.Queue.put = _real["queue_put"]
    queue.Queue.get = _real["queue_get"]
    sanitizer.set_race_listener(None)


def maybe_enable_from_env() -> bool:
    """Called at ``import tony_tpu`` so every subprocess of an armed run
    (executors, coordinators, pool workers, fleet daemons) joins."""
    if os.environ.get(ENV_FLAG, "").strip().lower() in ("1", "true", "on"):
        enable()
        return True
    return False


def _dump_at_exit() -> None:
    """Best-effort multi-process aggregation (the sanitizer's contract):
    a process with findings drops its report into $TONY_RACE_DETECTOR_DIR
    for the test session to collect."""
    d = os.environ.get(ENV_DIR, "")
    if not d or _state is None:
        return
    rep = _state.report()
    if not rep["races"]:
        return
    try:
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"race.{os.getpid()}.json")
        with open(path, "w", encoding="utf-8") as f:
            json.dump(rep, f, indent=1, sort_keys=True)
    except OSError:
        pass


def collect_reports(directory: Optional[str] = None) -> List[Dict[str, Any]]:
    """This process's report + any subprocess dumps in the directory."""
    out = [_global_state().report()]
    d = directory or os.environ.get(ENV_DIR, "")
    if d and os.path.isdir(d):
        for name in sorted(os.listdir(d)):
            if not name.startswith("race.") or not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(d, name), encoding="utf-8") as f:
                    out.append(json.load(f))
            except (OSError, ValueError):
                continue
    return out


def format_report(reports: List[Dict[str, Any]]) -> str:
    lines = []
    for rep in reports:
        for r in rep.get("races", []):
            lines.append(
                f"DATA RACE (pid {rep.get('pid')}): "
                f"{r['class']}.{r['field']} [{r['kind']}; declared "
                f"guard {r['guard']!r}]\n"
                f"  access A [{r['a']['thread']}] holding "
                f"{r['a']['locks'] or 'no locks'}\n"
                f"    at {r['a']['site']}\n"
                f"  access B [{r['b']['thread']}] holding "
                f"{r['b']['locks'] or 'no locks'}\n"
                f"    at {r['b']['site']}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Static side: the guarded-by lint rule family (tonylint integration)
# ---------------------------------------------------------------------------
RULES_RACE: Dict[str, str] = {
    "guarded-by": "GUARDED_BY-declared fields are only touched inside "
                  "`with self.<lock>:` (coordinator/ and fleet/)",
    "guarded-decl": "no undeclared shared-field stores outside __init__ "
                    "on GUARDED_BY-registered classes",
}

#: methods where guard-free access is legitimate: construction happens
#: before any thread exists, and the ``*_locked`` suffix is the
#: caller-holds-the-lock convention (documented in docs/development.md)
_EXEMPT_METHODS = ("__init__", "__new__")


def _class_registry(cls_node: ast.ClassDef,
                    src_lines: List[str]) -> Optional[Dict[str, Optional[str]]]:
    """Parse the class's guard declarations: the GUARDED_BY dict in the
    class body, plus trailing ``# guarded-by:`` comments anywhere in the
    class extent. None when the class declares nothing (uninstrumented —
    the rule family does not apply)."""
    reg: Optional[Dict[str, Optional[str]]] = None
    for stmt in cls_node.body:
        if (isinstance(stmt, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == GUARDED_ATTR
                        for t in stmt.targets)
                and isinstance(stmt.value, ast.Dict)):
            reg = {}
            for k, v in zip(stmt.value.keys, stmt.value.values):
                if not (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)):
                    continue
                guard: Optional[str] = None
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    guard = v.value
                reg[k.value] = guard
    end = getattr(cls_node, "end_lineno", None) or cls_node.lineno
    for lineno in range(cls_node.lineno, min(end, len(src_lines)) + 1):
        m = _COMMENT_GUARD_RE.search(src_lines[lineno - 1])
        if m:
            if reg is None:
                reg = {}
            reg.setdefault(m.group(1),
                           None if m.group(2) == "none" else m.group(2))
    return reg


def _in_with_guard(src: Any, node: ast.AST, guard: str,
                   method: ast.AST) -> bool:
    """Is ``node`` lexically inside ``with self.<guard>:`` within the
    method?"""
    parents = src.parent_map()
    cur = parents.get(node)
    while cur is not None and cur is not method:
        if isinstance(cur, ast.With):
            for item in cur.items:
                ce = item.context_expr
                if (isinstance(ce, ast.Attribute) and ce.attr == guard
                        and isinstance(ce.value, ast.Name)
                        and ce.value.id == "self"):
                    return True
        cur = parents.get(cur)
    return False


def run_race_rules(linter: Any, pkg_srcs: List[Any],
                   active: Set[str]) -> None:
    """Entry point called from tonylint.Linter.run() — same interface as
    protocol.run_protocol_rules."""
    if "guarded-by" not in active and "guarded-decl" not in active:
        return
    for src in pkg_srcs:
        in_scope = any((os.sep + d + os.sep) in src.rel
                       for d in ("coordinator", "fleet"))
        if not in_scope:
            continue
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                reg = _class_registry(node, src.lines)
                if reg is not None:
                    _check_class(linter, src, node, reg, active)


def _check_class(linter: Any, src: Any, cls_node: ast.ClassDef,
                 reg: Dict[str, Optional[str]], active: Set[str]) -> None:
    guards = {g for g in reg.values() if g}
    for stmt in cls_node.body:
        if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if stmt.name in _EXEMPT_METHODS:
            continue
        caller_holds = stmt.name.endswith("_locked")
        for sub in ast.walk(stmt):
            if not (isinstance(sub, ast.Attribute)
                    and isinstance(sub.value, ast.Name)
                    and sub.value.id == "self"):
                continue
            field = sub.attr
            guard = reg.get(field)
            if ("guarded-by" in active and guard is not None
                    and not caller_holds
                    and not _in_with_guard(src, sub, guard, stmt)):
                linter._emit(
                    "guarded-by", src.rel, sub.lineno,
                    f"{cls_node.name}.{field} is declared guarded-by "
                    f"{guard!r} but is touched outside `with "
                    f"self.{guard}:` (hold the lock, or do it in a "
                    f"*_locked helper whose callers hold it)", src)
            if ("guarded-decl" in active
                    and isinstance(sub.ctx, ast.Store)
                    and field not in reg
                    and field not in guards
                    and not field.startswith("__")):
                linter._emit(
                    "guarded-decl", src.rel, sub.lineno,
                    f"store to {cls_node.name}.{field} outside __init__ "
                    f"on a GUARDED_BY-registered class: declare it in "
                    f"the registry (with its lock, or None for "
                    f"atomic/single-writer-by-design fields)", src)


# ---------------------------------------------------------------------------
# No-deps self-check (CI lint job): the detector flags a textbook racy
# fixture and stays silent on the locked and handoff-rescued twins.
# ---------------------------------------------------------------------------
def _selfcheck() -> int:
    san = sanitizer.State()
    st = RaceState(san)

    class _Racy:
        GUARDED_BY = {"shared": "_mu"}

        def __init__(self) -> None:
            self.shared: Dict[str, int] = {}

    class _Clean:
        GUARDED_BY = {"shared": "_mu"}

        def __init__(self) -> None:
            self._mu = sanitizer.sanitize_lock(
                sanitizer.raw_lock(), "selfcheck:_mu", san)
            with self._mu:
                self.shared: Dict[str, int] = {}

    class _Handoff:
        GUARDED_BY = {"shared": "_mu"}

        def __init__(self) -> None:
            self.shared: Dict[str, int] = {}

    instrument_class(_Racy, state=st)
    instrument_class(_Clean, state=st)
    instrument_class(_Handoff, state=st)
    racy, clean, hand = _Racy(), _Clean(), _Handoff()

    def _touch_racy() -> None:
        racy.shared["k"] = 1

    def _touch_clean() -> None:
        with clean._mu:
            clean.shared["k"] = 1

    for fn in (_touch_racy, _touch_clean):
        t = threading.Thread(target=fn)
        t.start()
        t.join()
        # NOTE: no note_start/note_join on the isolated state — the
        # fixture threads must look concurrent to it.
        fn()
    # Handoff twin: same unlocked shape as _Racy, but the start/join
    # edges are injected — the HB graph must rescue it.
    t = threading.Thread(target=lambda: hand.shared.update(k=1))
    st.note_start(t)
    t.start()
    t.join()
    st.note_join(t)
    hand.shared["k"] = 2
    rep = st.report()
    racy_hits = [r for r in rep["races"] if r["class"] == "_Racy"]
    clean_hits = [r for r in rep["races"]
                  if r["class"] in ("_Clean", "_Handoff")]
    ok = bool(racy_hits) and not clean_hits
    print(f"tonyrace selfcheck: racy fixture -> "
          f"{len(racy_hits)} finding(s) (want >=1), locked + handoff "
          f"fixtures -> {len(clean_hits)} finding(s) (want 0)")
    if racy_hits:
        print(format_report([{"pid": os.getpid(), "races": racy_hits}]))
    return 0 if ok else 1


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m tony_tpu.devtools.race",
        description="tonyrace self-check (see docs/development.md).")
    p.parse_args(argv)
    return _selfcheck()


if __name__ == "__main__":
    sys.exit(main())
