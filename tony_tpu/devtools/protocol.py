"""tonylint v2 protocol rules: the control-plane contract, machine-checked.

The coordinator↔executor protocol is hand-maintained the same way the
reference's was — directives ride heartbeat responses, REC_* journal
records drive ``--recover`` replay, gen/mgen fences guard every frame,
beacon fields feed the metrics fold. None of that is declared anywhere:
each half lives in a different file, and PR 7's tonylint only checked
single-registry surfaces (conf keys, fault sites, EventTypes, the RPC
method table). These six rules extract BOTH halves of each protocol from
the AST and check them against each other, so the scheduler/journal
refactors ahead (ROADMAP items 1 and 5) cannot silently strand one side.

Rules (suppressed like every tonylint rule, ``# tony: lint-ignore[...]``):

=================  =========================================================
directive-parity   every directive key set on a heartbeat response in the
                   coordinator has an executor heartbeat branch reading it
                   (and vice versa); stateful (dict-payload) directives
                   have a dedup/mgen guard in their executor handler
journal-parity     every ``REC_*`` record type is appended somewhere and
                   has a ``replay()`` branch; replay handles no type that
                   is never written; record types are never literal strings
fence-coverage     every task-scoped RpcServer handler that mutates
                   Session state validates the epoch/membership fence
                   (``_check_epoch``/``_check_membership``) before mutating
beacon-parity      every field the executor ships in its heartbeat beacon
                   is read by a coordinator fold, and every read field has
                   a writer
terminal-state     no coordinator-package function assigns ``<task>.status``
                   without testing ``.terminal`` first (the journaled
                   epoch-reset/absorb/restore/replay paths are exempt)
metrics-registry   every exported ``tony_*`` series name is registered in
                   ``tony_tpu.metrics.SERIES`` exactly once, and every
                   registered series has an exporting call site
=================  =========================================================

Pure stdlib ``ast``, same contracts as tonylint.py: findings carry
file:line, the repo gate (tests/test_lint.py) asserts zero findings, and
each rule has a golden bad+clean fixture.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

#: rule id → one-line description, merged into tonylint.RULES
RULES_V2: Dict[str, str] = {
    "directive-parity": "heartbeat-response directives have executor "
                        "handler branches and dedup guards, both ways",
    "journal-parity": "REC_* record types are appended AND replayed; "
                      "no literal record types",
    "fence-coverage": "task-scoped RPC handlers that mutate Session "
                      "state validate gen/mgen before mutating",
    "beacon-parity": "executor beacon fields and coordinator fold "
                     "reads agree 1:1",
    "terminal-state": "no task status store without a .terminal guard "
                      "(epoch-reset/absorb paths exempt)",
    "metrics-registry": "tony_* series names live in metrics.SERIES, "
                        "each with an exporting call site",
}

#: Session methods that mutate the task matrix / failure state
#: (coordinator/session.py) — calling one from an RPC handler is a
#: state mutation the fence must precede.
_SESSION_MUTATORS = frozenset((
    "register_worker", "on_task_completed", "resize_job", "mark_killed",
    "fail", "restore_task", "mark_job_scheduled",
))

#: fence-validation call names — any one of them in the handler's
#: (delegate-resolved) body satisfies fence-coverage.
_FENCE_CALLS = ("check_epoch", "check_membership", "fences_frame")

#: functions exempt from terminal-state: the journaled epoch-reset,
#: absorb and recovery-restore paths legitimately write terminal or
#: post-terminal statuses (ISSUE: "except the journaled epoch-reset/
#: absorb paths"), and the journal replay fold applies records verbatim.
_TERMINAL_EXEMPT = re.compile(r"absorb|restore|reset|replay")

#: a tony_* series name — the package's own name ("tony_tpu...") is a
#: path/module reference, never a series.
_SERIES_NAME_RE = re.compile(r"^tony_(?!tpu(?:$|[_/.]))[a-z0-9_]+$")


def _under(src, dirname: str) -> bool:
    return (os.sep + dirname + os.sep) in src.rel


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _functions(tree: ast.AST) -> Iterable[ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _attr_chain(node: ast.AST) -> List[str]:
    """['self', '_c', 'session', 'get_task'] for self._c.session.get_task."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return list(reversed(parts))


def run_protocol_rules(linter, pkg_srcs: List, active: Set[str]) -> None:
    """Entry point called from tonylint.Linter.run()."""
    if "directive-parity" in active:
        _check_directive_parity(linter, pkg_srcs)
    if "journal-parity" in active:
        _check_journal_parity(linter, pkg_srcs)
    if "fence-coverage" in active:
        _check_fence_coverage(linter, pkg_srcs)
    if "beacon-parity" in active:
        _check_beacon_parity(linter, pkg_srcs)
    if "terminal-state" in active:
        _check_terminal_state(linter, pkg_srcs)
    if "metrics-registry" in active:
        _check_metrics_registry(linter, pkg_srcs)


# ---------------------------------------------------------------------------
# directive-parity
# ---------------------------------------------------------------------------
def _heartbeat_response_keys(srcs) -> Dict[str, Tuple[str, int, object]]:
    """Directive keys set on the response dict inside a coordinator
    heartbeat handler: ``resp["dump"] = ...`` in a function named
    ``heartbeat``/``task_executor_heartbeat`` under coordinator/."""
    keys: Dict[str, Tuple[str, int, object]] = {}
    for src in srcs:
        if not _under(src, "coordinator"):
            continue
        for fn in _functions(src.tree):
            if fn.name not in ("heartbeat", "task_executor_heartbeat"):
                continue
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Subscript)
                        and isinstance(node.targets[0].value, ast.Name)):
                    continue
                key = _const_str(node.targets[0].slice)
                if key and key != "ok":
                    keys.setdefault(key, (src.rel, node.lineno, src))
    return keys


def _executor_heartbeat_reads(srcs):
    """(reads, stateful, found_caller): keys the executor reads off the
    heartbeat RPC result, which of them are dict-payload (stateful), and
    whether any heartbeat call site exists at all. Flow-aware: only
    ``.get()`` calls on the variable the heartbeat result was assigned
    to, inside the function making the call."""
    reads: Dict[str, Tuple[str, int, object]] = {}
    stateful: Set[str] = set()
    found_caller = False
    for src in srcs:
        if not _under(src, "executor"):
            continue
        for fn in _functions(src.tree):
            res_vars: Set[str] = set()
            for node in ast.walk(fn):
                if (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)
                        and isinstance(node.value.func, ast.Attribute)
                        and node.value.func.attr == "call"
                        and node.value.args
                        and _const_str(node.value.args[0])
                        == "task_executor_heartbeat"):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            res_vars.add(t.id)
            if not res_vars:
                continue
            found_caller = True
            for node in ast.walk(fn):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "get"
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id in res_vars
                        and node.args):
                    key = _const_str(node.args[0])
                    if key and key != "ok":
                        reads.setdefault(key, (src.rel, node.lineno, src))
            for node in ast.walk(fn):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id == "isinstance"
                        and len(node.args) == 2
                        and isinstance(node.args[1], ast.Name)
                        and node.args[1].id == "dict"):
                    inner = node.args[0]
                    if (isinstance(inner, ast.Call)
                            and isinstance(inner.func, ast.Attribute)
                            and inner.func.attr == "get"
                            and isinstance(inner.func.value, ast.Name)
                            and inner.func.value.id in res_vars
                            and inner.args):
                        key = _const_str(inner.args[0])
                        if key:
                            stateful.add(key)
    return reads, stateful, found_caller


def _handler_has_dedup_guard(srcs, key: str) -> bool:
    """An executor-package function named after the directive contains a
    comparison/membership test over an mgen- or id-shaped identifier —
    the re-sent-every-beat dedup discipline."""
    for src in srcs:
        if not _under(src, "executor"):
            continue
        for fn in _functions(src.tree):
            if key not in fn.name:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Compare):
                    continue
                tokens: List[str] = []
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Name):
                        tokens.append(sub.id)
                    elif isinstance(sub, ast.Attribute):
                        tokens.append(sub.attr)
                if any("mgen" in t or "id" in t for t in tokens):
                    return True
    return False


def _check_directive_parity(linter, srcs) -> None:
    coord_keys = _heartbeat_response_keys(srcs)
    reads, stateful, found_caller = _executor_heartbeat_reads(srcs)
    if found_caller:
        for key, (rel, line, src) in sorted(coord_keys.items()):
            if key not in reads:
                linter._emit(
                    "directive-parity", rel, line,
                    f"directive {key!r} rides the heartbeat response but "
                    f"no executor heartbeat branch reads it — the "
                    f"directive is shipped and dropped on the floor", src)
    if coord_keys:
        for key, (rel, line, src) in sorted(reads.items()):
            if key not in coord_keys:
                linter._emit(
                    "directive-parity", rel, line,
                    f"executor reads directive {key!r} off the heartbeat "
                    f"response, but no coordinator heartbeat path sets "
                    f"it — dead handler branch", src)
    for key in sorted(stateful & set(coord_keys)):
        if not _handler_has_dedup_guard(srcs, key):
            rel, line, src = reads[key]
            linter._emit(
                "directive-parity", rel, line,
                f"stateful directive {key!r} is re-sent every beat but "
                f"its executor handler has no dedup/mgen guard — the "
                f"drain/capture would re-fire on every heartbeat", src)


# ---------------------------------------------------------------------------
# journal-parity
# ---------------------------------------------------------------------------
def _check_journal_parity(linter, srcs) -> None:
    # Every write-ahead journal module in the package (the session
    # journal coordinator/journal.py AND the fleet journal
    # fleet/journal.py) owes the same parity: REC_* declared ⇒ appended
    # somewhere ⇒ replayed by ITS OWN replay(). Constant names are
    # globally unique across journal modules, so the repo-wide
    # written-set matches writers to the right registry by name.
    journal_srcs = [s for s in srcs if s.rel.endswith("journal.py")]
    if not journal_srcs:
        return
    written: Set[str] = set()
    for src in srcs:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Dict):
                continue
            for k, v in zip(node.keys, node.values):
                if _const_str(k) != "t":
                    continue
                if isinstance(v, ast.Name) and v.id.startswith("REC_"):
                    written.add(v.id)
                elif _const_str(v) is not None:
                    linter._emit(
                        "journal-parity", src.rel, v.lineno,
                        f"journal record type {_const_str(v)!r} written "
                        f"as a string literal — use the REC_* constant "
                        f"so replay parity stays checkable", src)
    for journal_src in journal_srcs:
        # REC_* constants this journal module declares: name → (value, line)
        consts: Dict[str, Tuple[str, int]] = {}
        for node in journal_src.tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id.startswith("REC_")):
                val = _const_str(node.value)
                if val is not None:
                    consts[node.targets[0].id] = (val, node.lineno)
        if not consts:
            continue
        replayed: Set[str] = set()
        for fn in _functions(journal_src.tree):
            if fn.name != "replay":
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Compare):
                    continue
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Name) \
                            and sub.id.startswith("REC_"):
                        replayed.add(sub.id)
        for name in sorted(consts):
            val, line = consts[name]
            if name not in written:
                linter._emit(
                    "journal-parity", journal_src.rel, line,
                    f"journal record type {name} ({val!r}) is declared "
                    f"but never appended — dead record type (delete it, "
                    f"or wire the writer)", journal_src)
            elif name not in replayed:
                linter._emit(
                    "journal-parity", journal_src.rel, line,
                    f"journal record type {name} ({val!r}) is appended "
                    f"but replay() has no branch for it — a recover "
                    f"replay silently drops this state transition",
                    journal_src)
        for name in sorted(replayed - set(consts)):
            linter._emit(
                "journal-parity", journal_src.rel, 1,
                f"replay() references record type {name} which is not "
                f"a declared REC_* constant", journal_src)


# ---------------------------------------------------------------------------
# fence-coverage
# ---------------------------------------------------------------------------
def _service_classes(src) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(src.tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "RpcServer" and node.args):
            first = node.args[0]
            if isinstance(first, ast.Call) and isinstance(first.func,
                                                          ast.Name):
                out.add(first.func.id)
            elif isinstance(first, ast.Name):
                out.add(first.id)
    return out


def _module_methods(src) -> Dict[str, ast.FunctionDef]:
    """Every method/function name → def node in the file (last wins);
    good enough to resolve one file's delegation chains."""
    out: Dict[str, ast.FunctionDef] = {}
    for fn in _functions(src.tree):
        out[fn.name] = fn
    return out


def _effective_nodes(handler: ast.FunctionDef,
                     methods: Dict[str, ast.FunctionDef],
                     depth: int = 2) -> List[ast.AST]:
    """The handler's body plus same-file methods it calls through
    ``self`` / ``self._x`` attributes, resolved ``depth`` hops deep —
    the wrapper-delegates-to-coordinator shape."""
    # Track resolved DEF NODES, not names: a thin RPC wrapper usually
    # delegates to a same-named coordinator method in the same file.
    seen: Set[int] = {id(handler)}
    frontier = [handler]
    nodes: List[ast.AST] = [handler]
    for _ in range(depth):
        nxt: List[ast.FunctionDef] = []
        for fn in frontier:
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)):
                    continue
                chain = _attr_chain(node.func)
                if not chain or chain[0] != "self":
                    continue
                target = methods.get(node.func.attr)
                if target is None or id(target) in seen:
                    continue
                seen.add(id(target))
                nxt.append(target)
                nodes.append(target)
        frontier = nxt
    return nodes


def _mutates_session(nodes: List[ast.AST]) -> Optional[int]:
    """Line of the first Session-state mutation under ``nodes``:
    a mutator call on a ``.session`` chain, or an attribute store on a
    variable obtained from ``session.get_task(...)``."""
    task_vars: Set[str] = set()
    for scope in nodes:
        for node in ast.walk(scope):
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and isinstance(node.value.func, ast.Attribute)
                    and node.value.func.attr == "get_task"
                    and "session" in _attr_chain(node.value.func)):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        task_vars.add(t.id)
    for scope in nodes:
        for node in ast.walk(scope):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SESSION_MUTATORS
                    and "session" in _attr_chain(node.func)):
                return node.lineno
            if (isinstance(node, ast.Assign) and node.targets
                    and isinstance(node.targets[0], ast.Attribute)
                    and isinstance(node.targets[0].value, ast.Name)
                    and node.targets[0].value.id in task_vars):
                return node.lineno
    return None


def _has_fence_call(nodes: List[ast.AST]) -> bool:
    for scope in nodes:
        for node in ast.walk(scope):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and any(f in node.func.attr for f in _FENCE_CALLS)):
                return True
    return False


def _check_fence_coverage(linter, srcs) -> None:
    for src in srcs:
        classes = _service_classes(src)
        if not classes:
            continue
        methods = _module_methods(src)
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.ClassDef)
                    and node.name in classes):
                continue
            for item in node.body:
                if not isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if item.name.startswith("_"):
                    continue
                params = {a.arg for a in item.args.args}
                if "task_id" not in params:
                    # Operator/client surface (kill, resize, report):
                    # not an executor frame — the task-scoped fences do
                    # not apply.
                    continue
                nodes = _effective_nodes(item, methods)
                mut_line = _mutates_session(nodes)
                if mut_line is None:
                    continue
                if not _has_fence_call(nodes):
                    linter._emit(
                        "fence-coverage", src.rel, item.lineno,
                        f"RPC handler {item.name!r} mutates Session "
                        f"state (line {mut_line}) without validating "
                        f"the epoch/membership fence first — a stale-"
                        f"epoch executor frame can corrupt the live "
                        f"gang's state", src)


# ---------------------------------------------------------------------------
# beacon-parity
# ---------------------------------------------------------------------------
def _check_beacon_parity(linter, srcs) -> None:
    writes: Dict[str, Tuple[str, int, object]] = {}
    for src in srcs:
        if not _under(src, "executor"):
            continue
        for fn in _functions(src.tree):
            if "beacon" not in fn.name:
                continue
            # Only fields of the dict the function RETURNS count as the
            # beacon surface — nested sub-dicts (the "metrics" payload)
            # have their own keys and are folded as one field.
            returned: Set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Return) and node.value is not None:
                    for sub in ast.walk(node.value):
                        if isinstance(sub, ast.Name):
                            returned.add(sub.id)
            for node in ast.walk(fn):
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Subscript)
                        and isinstance(node.targets[0].value, ast.Name)
                        and node.targets[0].value.id in returned):
                    key = _const_str(node.targets[0].slice)
                    if key:
                        writes.setdefault(key,
                                          (src.rel, node.lineno, src))
    reads: Dict[str, Tuple[str, int, object]] = {}
    for src in srcs:
        if not _under(src, "coordinator"):
            continue
        for node in ast.walk(src.tree):
            # progress.get("field")
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "get"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "progress"
                    and node.args):
                key = _const_str(node.args[0])
                if key:
                    reads.setdefault(key, (src.rel, node.lineno, src))
            # "field" in progress  /  "field" not in progress
            if (isinstance(node, ast.Compare)
                    and len(node.ops) == 1
                    and isinstance(node.ops[0], (ast.In, ast.NotIn))
                    and isinstance(node.comparators[0], ast.Name)
                    and node.comparators[0].id == "progress"):
                key = _const_str(node.left)
                if key:
                    reads.setdefault(key, (src.rel, node.lineno, src))
            # progress["field"]
            if (isinstance(node, ast.Subscript)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "progress"
                    and isinstance(node.ctx, ast.Load)):
                key = _const_str(node.slice)
                if key:
                    reads.setdefault(key, (src.rel, node.lineno, src))
    if not writes or not reads:
        return
    for key, (rel, line, src) in sorted(writes.items()):
        if key not in reads:
            linter._emit(
                "beacon-parity", rel, line,
                f"beacon field {key!r} is shipped on every heartbeat "
                f"but no coordinator fold reads it — dead payload "
                f"(delete it, or wire the fold)", src)
    for key, (rel, line, src) in sorted(reads.items()):
        if key not in writes:
            linter._emit(
                "beacon-parity", rel, line,
                f"coordinator fold reads beacon field {key!r}, which no "
                f"executor beacon writes — the branch can never fire",
                src)


# ---------------------------------------------------------------------------
# terminal-state
# ---------------------------------------------------------------------------
def _check_terminal_state(linter, srcs) -> None:
    for src in srcs:
        if not _under(src, "coordinator"):
            continue
        for fn in _functions(src.tree):
            if _TERMINAL_EXEMPT.search(fn.name):
                continue
            stores = [
                node for node in ast.walk(fn)
                if isinstance(node, ast.Assign) and node.targets
                and isinstance(node.targets[0], ast.Attribute)
                and node.targets[0].attr == "status"
                and isinstance(node.targets[0].value, ast.Name)
                # self.status is the SESSION reduction, not a task
                # transition — tasks arrive as locals (t, task).
                and node.targets[0].value.id != "self"]
            if not stores:
                continue
            guarded = any(
                isinstance(node, ast.Attribute)
                and node.attr == "terminal"
                for node in ast.walk(fn))
            if guarded:
                continue
            for node in stores:
                linter._emit(
                    "terminal-state", src.rel, node.lineno,
                    f"{fn.name!r} assigns a task status without testing "
                    f".terminal first — a transition out of SUCCEEDED/"
                    f"FAILED/KILLED resurrects a closed task identity "
                    f"(only the journaled epoch-reset/absorb paths may)",
                    src)


# ---------------------------------------------------------------------------
# metrics-registry
# ---------------------------------------------------------------------------
def _check_metrics_registry(linter, srcs) -> None:
    from tony_tpu.metrics import SERIES

    referenced: Set[str] = set()
    metrics_src = None
    for src in srcs:
        if src.rel.endswith(os.path.join("tony_tpu", "metrics.py")):
            metrics_src = src
            # The registry file itself defines the names; its literals
            # are the registry, not references.
            continue
        for node in ast.walk(src.tree):
            name = _const_str(node)
            if name is None or not _SERIES_NAME_RE.match(name):
                continue
            referenced.add(name)
            if name in SERIES:
                continue
            # A prefix of a registered family is a deliberate family
            # match (the portal filters rendered lines by startswith),
            # same shape as conf-key's key-family mentions.
            if any(k.startswith(name) for k in SERIES):
                continue
            linter._emit(
                "metrics-registry", src.rel, node.lineno,
                f"series {name!r} is not registered in "
                f"tony_tpu.metrics.SERIES — the docs/portal/benchdiff "
                f"surfaces can't see it (register it, with its help "
                f"line, or fix the typo)", src)
    series_line = 1
    if metrics_src is not None:
        for node in metrics_src.tree.body:
            if (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name) and t.id == "SERIES"
                            for t in node.targets)):
                series_line = node.lineno
                break
    for name in sorted(set(SERIES) - referenced):
        linter._emit(
            "metrics-registry",
            metrics_src.rel if metrics_src else "tony_tpu/metrics.py",
            series_line,
            f"series {name!r} is registered in metrics.SERIES but "
            f"nothing in the package references it — dead registry "
            f"entry (delete it, or wire the exporter)", metrics_src)
