"""Developer tooling: machine-checkable invariants for the orchestrator.

Two halves, one discipline (docs/development.md):

- ``tonylint`` — an AST-based static pass over the ``tony_tpu`` package
  that enforces the project's implicit registries (conf keys, fault
  sites, event types, RPC surface) and coding disciplines (durable
  writes, monotonic clocks, span/thread hygiene, no blocking under
  coordinator locks). Run it with ``tony-tpu lint``; it also runs inside
  tier-1 (``tests/test_lint.py``) and as its own CI job.
- ``sanitizer`` — a runtime lock sanitizer (env flag
  ``TONY_LOCK_SANITIZER=1``) that records the lock-order graph and
  hold-while-blocking hazards across the whole tier-1 suite.
"""
