"""Developer tooling: machine-checkable invariants for the orchestrator.

Four layers, one discipline (docs/development.md):

- ``tonylint`` — an AST-based static pass over the ``tony_tpu`` package
  that enforces the project's implicit registries (conf keys, fault
  sites, event types, RPC surface) and coding disciplines (durable
  writes, monotonic clocks, span/thread hygiene, no blocking under
  coordinator locks). Run it with ``tony-tpu lint``; it also runs inside
  tier-1 (``tests/test_lint.py``) and as its own CI job.
- ``protocol`` — tonylint's v2 rule module: six flow-aware rules that
  extract BOTH halves of the coordinator↔executor protocol (heartbeat
  directives, journal record types, gen/mgen fences, beacon fields,
  terminal-state discipline, the metrics-series registry) and check
  them against each other.
- ``invariants`` — the runtime counterpart: ``tony-tpu check`` verifies
  a finished job dir's artifacts (journal, span log, perf, metrics)
  against the same protocol; auto-armed over every e2e/virtual-gang
  drill by ``tests/conftest.py``.
- ``sanitizer`` — a runtime lock sanitizer (env flag
  ``TONY_LOCK_SANITIZER=1``) that records the lock-order graph and
  hold-while-blocking hazards across the whole tier-1 suite.

The strict-core typecheck gate (``mypy --strict`` over
``pyproject.toml [tool.mypy]``) covers this package end to end.
"""
