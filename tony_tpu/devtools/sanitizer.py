"""Runtime lock sanitizer: lock-order graph + hold-while-blocking hazards.

TF-Replicator and Podracer (PAPERS.md) both observe that control-plane
concurrency bugs — not numerics — dominate orchestrator failures, and the
static side of that insurance (tonylint's ``lock-blocking`` rule) can only
see lexical ``with self._lock:`` blocks. This module watches the REAL
locks at runtime:

- **lock-order graph**: every time a thread acquires lock B while holding
  lock A, the edge (A → B) is recorded, keyed by the locks' allocation
  sites (``file:line`` of the ``threading.Lock()`` call). A cycle in that
  graph is a potential deadlock even if the interleaving that would
  deadlock never happened in this run — the classic lock-order-inversion
  detector (TSan's deadlock detector, ordered-lock disciplines).
- **hold-while-blocking hazards**: a thread that calls a blocking
  primitive (``time.sleep``, ``os.fsync``, ``subprocess.Popen.wait``,
  ``threading.Event.wait``, ``socket.create_connection``) while holding
  any sanitized lock stalls every other thread that needs that lock —
  the exact shape that turned a one-caller RPC outage into a stalled
  heartbeat thread (rpc/wire.py's old backoff-under-lock).

Scope: only locks ALLOCATED from ``tony_tpu`` code are sanitized — the
factory inspects the allocating frame, so stdlib internals (queue,
logging, threading.Event's own condition) and third-party libraries
(jax!) keep raw primitives and zero overhead. Blocking-primitive patches
cost one thread-local read when no sanitized lock is held.

Enablement: ``TONY_LOCK_SANITIZER=1`` in the environment (checked at
``import tony_tpu`` so executor/coordinator subprocesses inherit it), or
``enable()`` directly. ``tests/conftest.py`` turns it on for the whole
tier-1 suite and fails the session on any cycle or hazard. With
``TONY_LOCK_SANITIZER_DIR`` set, a process with findings dumps them there
at exit so multi-process e2e drills aggregate into the same verdict.

Unit tests construct an isolated :class:`State` and wrap locks through
:func:`sanitize_lock` directly — no global patching, no cross-test bleed.
"""

from __future__ import annotations

import atexit
import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Set, Tuple

ENV_FLAG = "TONY_LOCK_SANITIZER"
ENV_DIR = "TONY_LOCK_SANITIZER_DIR"

#: cap stored hazards/edges so a pathological loop cannot eat the heap
_MAX_HAZARDS = 200


def _site_of_frame(depth: int = 2, any_file: bool = False) -> Optional[str]:
    """Allocation/call site ``relpath:line`` if the frame is tony_tpu
    code (excluding this module), else None — or, with ``any_file``, the
    raw ``basename:line`` of whatever frame called (hazard labels)."""
    try:
        f = sys._getframe(depth)
    except ValueError:
        return None
    while f is not None:
        fn = f.f_code.co_filename
        if not fn.endswith(os.path.join("devtools", "sanitizer.py")):
            break
        f = f.f_back
    if f is None:
        return None
    fn = f.f_code.co_filename
    if "tony_tpu" not in fn:
        if any_file:
            return f"{os.path.basename(fn)}:{f.f_lineno}"
        return None
    idx = fn.rfind("tony_tpu")
    return f"{fn[idx:]}:{f.f_lineno}"


class State:
    """All sanitizer bookkeeping. The module keeps one global instance;
    tests build their own for isolation."""

    def __init__(self) -> None:
        # Raw primitives on purpose: the sanitizer must never sanitize
        # its own internals.
        self._mu = _REAL_LOCK()
        self._tls = threading.local()
        #: (site_a, site_b) -> one example {thread, blocking site}
        self.edges: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self.hazards: List[Dict[str, Any]] = []
        self._hazard_keys: Set[Tuple[str, str, Tuple[str, ...]]] = set()
        self.lock_sites: Set[str] = set()
        #: race-detector hookup (devtools/race.py RaceState, duck-typed
        #: to avoid the circular import): final lock release publishes a
        #: happens-before edge (``send``), first acquire receives one
        #: (``recv``), and the race detector reads locksets off
        #: :meth:`held_locks`.
        self.race: Optional[Any] = None

    # -- held-lock bookkeeping (thread-local) ----------------------------
    def _held(self) -> List[List[Any]]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def held_locks(self) -> Tuple[Any, ...]:
        """The calling thread's current lockset (wrapper objects, outer-
        most first) — the race detector's per-access lockset source."""
        held = getattr(self._tls, "held", None)
        if not held:
            return ()
        return tuple(entry[0] for entry in held)

    def note_acquired(self, lock: "SanitizedLock") -> None:
        held = self._held()
        for entry in held:
            if entry[0] is lock:
                entry[2] += 1           # reentrant re-acquire: no edge
                return
        new_edges = []
        for entry in held:
            a = entry[1]
            if a != lock.site:
                new_edges.append((a, lock.site))
        held.append([lock, lock.site, 1])
        if new_edges:
            with self._mu:
                for edge in new_edges:
                    self.edges.setdefault(edge, {
                        "thread": threading.current_thread().name,
                        "at": _site_of_frame(3) or "?"})
        if self.race is not None:
            self.race.recv(lock)        # release→acquire HB edge (in)

    def note_released(self, lock: "SanitizedLock") -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is lock:
                held[i][2] -= 1
                if held[i][2] <= 0:
                    if self.race is not None:
                        # Final release: publish everything this thread
                        # did while holding (release→acquire HB edge).
                        self.race.send(lock)
                    del held[i]
                return

    def register_lock(self, site: str) -> None:
        with self._mu:
            self.lock_sites.add(site)

    # -- blocking-primitive intake ---------------------------------------
    def note_blocking(self, what: str, where: Optional[str] = None) -> None:
        """Record a hazard if the calling thread holds any sanitized
        lock. ``where`` defaults to the caller's tony_tpu call site.

        Blocking issued by stdlib primitive INTERNALS is exempt: a
        ``Thread.start()`` waits (bounded, microseconds) on the new
        thread's boot event, and ``Popen.wait`` polls with internal
        sleeps — those are implementation details of calls the holder
        made, not independent blocking the holder wrote. (The outer
        ``Popen.wait`` call itself is still caught at the caller's
        frame.)"""
        held = getattr(self._tls, "held", None)
        if not held:
            return
        if where is None:
            where = _site_of_frame(2, any_file=True) or "?"
            if where.rsplit(":", 1)[0] in ("threading.py",
                                           "subprocess.py"):
                return
        sites = tuple(sorted({e[1] for e in held}))
        key = (what, where, sites)
        with self._mu:
            if key in self._hazard_keys or \
                    len(self.hazards) >= _MAX_HAZARDS:
                return
            self._hazard_keys.add(key)
            self.hazards.append({
                "blocking": what, "where": where, "held": list(sites),
                "thread": threading.current_thread().name})

    # -- reporting -------------------------------------------------------
    def cycles(self) -> List[List[str]]:
        """Cycles in the lock-order site graph (each reported once,
        rotated to its lexicographically-smallest node)."""
        with self._mu:
            graph: Dict[str, Set[str]] = {}
            for a, b in self.edges:
                graph.setdefault(a, set()).add(b)
                graph.setdefault(b, set())
        seen_cycles: Set[Tuple[str, ...]] = set()
        out: List[List[str]] = []
        WHITE, GRAY, BLACK = 0, 1, 2
        color = {n: WHITE for n in graph}
        stack: List[str] = []

        def visit(n: str) -> None:
            color[n] = GRAY
            stack.append(n)
            for m in sorted(graph[n]):
                if color[m] == GRAY:
                    cyc = stack[stack.index(m):]
                    k = min(range(len(cyc)), key=lambda i: cyc[i])
                    canon = tuple(cyc[k:] + cyc[:k])
                    if canon not in seen_cycles:
                        seen_cycles.add(canon)
                        out.append(list(canon))
                elif color[m] == WHITE:
                    visit(m)
            stack.pop()
            color[n] = BLACK

        for n in sorted(graph):
            if color[n] == WHITE:
                visit(n)
        return out

    def report(self) -> Dict[str, Any]:
        with self._mu:
            hazards = list(self.hazards)
            n_edges = len(self.edges)
            n_locks = len(self.lock_sites)
        return {"pid": os.getpid(), "cycles": self.cycles(),
                "hazards": hazards, "edges": n_edges,
                "locks_sanitized": n_locks}

    def clear(self) -> None:
        with self._mu:
            self.edges.clear()
            self.hazards.clear()
            self._hazard_keys.clear()


class SanitizedLock:
    """Duck-typed Lock/RLock wrapper feeding a :class:`State`. Supports
    the full primitive surface tony_tpu uses: acquire/release, context
    manager, ``locked()``."""

    def __init__(self, inner: Any, site: str, state: State) -> None:
        self._inner = inner
        self.site = site
        self._state = state
        state.register_lock(site)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._state.note_acquired(self)
        return got

    def release(self) -> None:
        self._state.note_released(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<SanitizedLock {self.site} of {self._inner!r}>"


def sanitize_lock(inner: Any, site: str,
                  state: Optional[State] = None) -> SanitizedLock:
    """Wrap an existing primitive for an explicit State — the unit-test
    entry point (no global patching involved)."""
    return SanitizedLock(inner, site, state or _state)


def io_lock() -> Any:
    """A lock whose PURPOSE is to serialize blocking I/O (one log fetch
    per task handle, one upload per artifact): holding it across
    Popen.wait/fsync is the design, not a hazard, so it is allocated
    raw and excluded from sanitizer tracking. Use sparingly — a lock
    any RPC handler or monitor tick can contend for does NOT qualify."""
    return _REAL_LOCK()


def raw_lock() -> Any:
    """An always-raw Lock for the checker tooling's OWN internals (the
    race detector's bookkeeping mutex): never wrapped, never tracked,
    regardless of when the factories were patched."""
    return _REAL_LOCK()


class SanitizedEvent:
    """Event wrapper for tony allocation sites: ``set`` → successful
    ``wait`` is a happens-before handoff edge for the race detector
    (devtools/race.py). The blocking wait itself still feeds
    hold-while-blocking through the class-level patch on the real
    Event — this wrapper only adds the HB half that was invisible."""

    def __init__(self, inner: Any, site: str, state: State) -> None:
        self._inner = inner
        self.site = site
        self._state = state

    def set(self) -> None:
        if self._state.race is not None:
            self._state.race.send(self)
        self._inner.set()

    def clear(self) -> None:
        self._inner.clear()

    def is_set(self) -> bool:
        return bool(self._inner.is_set())

    def wait(self, timeout: Optional[float] = None) -> bool:
        got = bool(self._inner.wait(timeout))
        if got and self._state.race is not None:
            self._state.race.recv(self)
        return got

    def __repr__(self) -> str:
        return f"<SanitizedEvent {self.site} of {self._inner!r}>"


class SanitizedCondition:
    """Condition wrapper for tony allocation sites (bare
    ``threading.Condition()`` — today these are invisible to the
    sanitizer). It is lock-shaped: acquire/release feed the lock-order
    graph and the thread's lockset exactly like a SanitizedLock, and
    ``wait`` (1) DROPS the condition from the lockset for its duration —
    the underlying primitive releases its lock, so holding it across the
    wait is the design, not a hazard — (2) records hold-while-blocking
    against any OTHER sanitized locks still held, and (3) receives the
    notify side's happens-before edge."""

    def __init__(self, inner: Any, site: str, state: State) -> None:
        self._inner = inner
        self.site = site
        self._state = state
        state.register_lock(site)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._state.note_acquired(self)  # type: ignore[arg-type]
        return bool(got)

    def release(self) -> None:
        self._state.note_released(self)      # type: ignore[arg-type]
        self._inner.release()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        self._state.note_released(self)      # type: ignore[arg-type]
        self._state.note_blocking("threading.Condition.wait")
        try:
            got = bool(self._inner.wait(timeout))
        finally:
            self._state.note_acquired(self)  # type: ignore[arg-type]
        if got and self._state.race is not None:
            self._state.race.recv(self)
        return got

    def wait_for(self, predicate: Any,
                 timeout: Optional[float] = None) -> Any:
        """Stdlib-shaped wait_for, routed through :meth:`wait` so every
        underlying wait keeps the lockset/HB bookkeeping."""
        endtime: Optional[float] = None
        waittime = timeout
        result = predicate()
        while not result:
            if waittime is not None:
                if endtime is None:
                    endtime = time.monotonic() + waittime
                else:
                    waittime = endtime - time.monotonic()
                    if waittime <= 0:
                        break
            self.wait(waittime)
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        if self._state.race is not None:
            self._state.race.send(self)
        self._inner.notify(n)

    def notify_all(self) -> None:
        if self._state.race is not None:
            self._state.race.send(self)
        self._inner.notify_all()

    def __repr__(self) -> str:
        return f"<SanitizedCondition {self.site} of {self._inner!r}>"


# ---------------------------------------------------------------------------
# Global enablement: patch the factories + blocking primitives
# ---------------------------------------------------------------------------
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_EVENT = threading.Event
_REAL_CONDITION = threading.Condition
_state = State()
_enabled = False
_real: Dict[str, Any] = {}


def state() -> State:
    return _state


def enabled() -> bool:
    return _enabled


def set_race_listener(race: Optional[Any]) -> None:
    """Attach (or detach) the race detector to the GLOBAL sanitizer
    state: lock acquire/release then feed its happens-before graph, and
    it reads locksets via State.held_locks()."""
    _state.race = race


def _lock_factory() -> Any:
    site = _site_of_frame(2)
    inner = _REAL_LOCK()
    if site is None:
        return inner
    return SanitizedLock(inner, site, _state)


def _rlock_factory() -> Any:
    site = _site_of_frame(2)
    inner = _REAL_RLOCK()
    if site is None:
        return inner
    return SanitizedLock(inner, site, _state)


def _event_factory() -> Any:
    site = _site_of_frame(2)
    inner = _REAL_EVENT()
    if site is None:
        return inner
    return SanitizedEvent(inner, site, _state)


def _condition_factory(lock: Optional[Any] = None) -> Any:
    # Explicit-lock conditions keep the raw primitive: the lock they
    # wrap is already sanitized if it came from a tony factory, and the
    # real Condition drives it by duck-typing. (No such allocation site
    # exists in the package today — bare Condition() is the shape.)
    if lock is not None:
        return _REAL_CONDITION(lock)
    site = _site_of_frame(2)
    inner = _REAL_CONDITION()
    if site is None:
        return inner
    return SanitizedCondition(inner, site, _state)


def enable() -> None:
    """Patch lock factories + blocking primitives (idempotent)."""
    global _enabled
    if _enabled:
        return
    _enabled = True
    import socket
    import subprocess

    threading.Lock = _lock_factory          # type: ignore[assignment]
    threading.RLock = _rlock_factory        # type: ignore[assignment]
    # Event/Condition allocation sites are wrapped the same way — their
    # set→wait / notify→wait handoffs feed the race detector's HB graph,
    # and Condition.wait (previously invisible) now feeds
    # hold-while-blocking. Stdlib-internal allocations (queue.Queue's
    # conditions!) see a non-tony frame and stay raw.
    threading.Event = _event_factory        # type: ignore[misc,assignment]
    threading.Condition = _condition_factory  # type: ignore[misc,assignment]

    _real["sleep"] = time.sleep

    def _sleep(secs: float) -> None:
        if secs and secs > 0:
            _state.note_blocking("time.sleep")
        _real["sleep"](secs)

    time.sleep = _sleep

    _real["fsync"] = os.fsync

    def _fsync(fd: int) -> None:
        _state.note_blocking("os.fsync")
        _real["fsync"](fd)

    os.fsync = _fsync

    _real["popen_wait"] = subprocess.Popen.wait

    def _popen_wait(self: Any, timeout: Optional[float] = None) -> int:
        _state.note_blocking("subprocess.Popen.wait")
        return _real["popen_wait"](self, timeout)

    subprocess.Popen.wait = _popen_wait     # type: ignore[method-assign]

    _real["event_wait"] = _REAL_EVENT.wait

    def _event_wait(self: Any, timeout: Optional[float] = None) -> bool:
        _state.note_blocking("threading.Event.wait")
        return _real["event_wait"](self, timeout)

    _REAL_EVENT.wait = _event_wait          # type: ignore[method-assign]

    _real["create_connection"] = socket.create_connection

    def _create_connection(*a: Any, **k: Any) -> Any:
        _state.note_blocking("socket.create_connection")
        return _real["create_connection"](*a, **k)

    socket.create_connection = _create_connection
    atexit.register(_dump_at_exit)


def disable() -> None:
    """Restore the real primitives. Locks already wrapped stay wrapped
    (they keep working; they just stop being joined by new ones)."""
    global _enabled
    if not _enabled:
        return
    _enabled = False
    import socket
    import subprocess

    threading.Lock = _REAL_LOCK             # type: ignore[assignment]
    threading.RLock = _REAL_RLOCK           # type: ignore[assignment]
    threading.Event = _REAL_EVENT           # type: ignore[misc]
    threading.Condition = _REAL_CONDITION   # type: ignore[misc]
    time.sleep = _real["sleep"]
    os.fsync = _real["fsync"]
    subprocess.Popen.wait = _real["popen_wait"]
    _REAL_EVENT.wait = _real["event_wait"]  # type: ignore[method-assign]
    socket.create_connection = _real["create_connection"]


def maybe_enable_from_env() -> bool:
    """Called at ``import tony_tpu`` so every subprocess in a sanitized
    run (executors, the coordinator, pool workers) joins in."""
    if os.environ.get(ENV_FLAG, "").strip().lower() in ("1", "true", "on"):
        enable()
        return True
    return False


def _dump_at_exit() -> None:
    """Best-effort multi-process aggregation: a process with findings
    drops its report into $TONY_LOCK_SANITIZER_DIR for the test session
    to collect (os._exit fault paths skip this — by design, the fault IS
    the teardown-free crash)."""
    d = os.environ.get(ENV_DIR, "")
    if not d:
        return
    rep = _state.report()
    if not rep["cycles"] and not rep["hazards"]:
        return
    try:
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"sanitizer.{os.getpid()}.json")
        with open(path, "w", encoding="utf-8") as f:
            json.dump(rep, f, indent=1, sort_keys=True)
    except OSError:
        pass


def collect_reports(directory: Optional[str] = None) -> List[Dict[str, Any]]:
    """This process's report + any subprocess dumps in the directory."""
    out = [_state.report()]
    d = directory or os.environ.get(ENV_DIR, "")
    if d and os.path.isdir(d):
        for name in sorted(os.listdir(d)):
            if not name.startswith("sanitizer.") or \
                    not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(d, name), encoding="utf-8") as f:
                    out.append(json.load(f))
            except (OSError, ValueError):
                continue
    return out


def format_report(reports: List[Dict[str, Any]]) -> str:
    lines = []
    for rep in reports:
        for cyc in rep.get("cycles", []):
            lines.append(
                f"LOCK-ORDER CYCLE (pid {rep.get('pid')}): "
                + " -> ".join(cyc + [cyc[0]]))
        for hz in rep.get("hazards", []):
            lines.append(
                f"HOLD-WHILE-BLOCKING (pid {rep.get('pid')}): "
                f"{hz['blocking']} at {hz['where']} while holding "
                f"{', '.join(hz['held'])} [thread {hz['thread']}]")
    return "\n".join(lines)
