"""tonylint — project-specific static analysis for the tony-tpu control plane.

Seven PRs in, the orchestrator's correctness rests on implicit registries
(conf keys, fault sites, ``EventType`` members, the RPC method surface)
and disciplines (durable job-dir writes, monotonic deadline clocks, span
and thread hygiene, no blocking under coordinator locks) that were
enforced only by convention and a couple of one-off parity smokes. The
reference made exactly this a first-class concern — its
``TestTonyConfigurationFields.java`` gates keys↔defaults agreement — and
this module generalizes that to every registry the project grew since.

Pure stdlib ``ast``; no third-party linter framework. Scope: the
``tony_tpu`` package (rule ``rpc-parity`` additionally reads ``tests/``
for call sites, so a handler only tests exercise is not "dead").

Rules (ids are what ``# tony: lint-ignore[<rule>]`` suppresses):

==============  ============================================================
conf-key        every ``tony.*`` dotted token in a string literal outside
                ``conf/keys.py`` must resolve to a registered ConfigKey, a
                dynamic per-jobtype key, or a registered key family prefix
fault-site      ``faults.fire/check/fire_amount/check_partition`` call
                sites use literal site names from ``faults.SITES``; every
                listed site has at least one call site (both directions,
                like the reference's fault-hook constants)
event-type      events are built only from live ``EventType`` members;
                ``diagnosis/rules.py`` ``events_used`` tuples and
                ``events_of("...")`` strings reference only live members
rpc-parity      every method name a client ``.call("...")``s has a
                registered server handler, and every handler has at least
                one call site (package or tests) — no dead surface
durable-write   no hand-rolled ``os.replace`` outside ``utils/durable.py``
                and no bare ``open(..., "w")`` targeting a job-dir
                artifact: route through ``atomic_write``/``AppendLog``/
                ``durable_replace`` so a torn write is never adopted
clock           ``time.time()`` must not feed deadline/duration arithmetic
                (+/- or comparisons) — monotonic only; wall time is for
                timestamp anchors (bare assignment, ``* 1000`` stamps)
span-leak       a span from ``start_span`` must be context-managed or have
                a matching ``.end(`` (same function for locals, same
                module for ``self._x`` spans)
thread-leak     ``threading.Thread`` must be daemonized or joined in the
                constructing function
lock-blocking   no blocking calls (sleep, wait, join, rpc ``.call``,
                fsync, subprocess) inside ``with self._lock:`` bodies in
                ``coordinator/`` modules
bare-except     no ``except:`` — name what you catch
defaults-md     ``conf/defaults.md`` is exactly the registry's rendered
                table (the reference keys↔defaults-file parity gate)
==============  ============================================================

Six further v2 *protocol* rules (directive-parity, journal-parity,
fence-coverage, beacon-parity, terminal-state, metrics-registry) extract
both halves of the coordinator↔executor protocol — heartbeat directives,
REC_* journal record types, gen/mgen fences, beacon fields, terminal
task-state discipline, the tony_* metrics registry — and check them
against each other; they live in ``devtools/protocol.py`` and their
runtime counterparts in ``devtools/invariants.py`` (``tony-tpu check``).

Output contract: findings carry ``file:line`` + rule id; the CLI
(``tony-tpu lint``) exits nonzero on any finding and can emit JSON; the
tier-1 test (``tests/test_lint.py``) asserts a clean repo, so deleting a
still-referenced conf key, fault site or EventType member fails the
suite with the exact reference location.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import sys
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tony_tpu.devtools.protocol import RULES_V2, run_protocol_rules
from tony_tpu.devtools.race import RULES_RACE, run_race_rules

#: rule id → one-line description (the ``--list`` surface and the doc table)
RULES: Dict[str, str] = {
    "conf-key": "tony.* string literals resolve to registered config keys",
    "fault-site": "faults.fire/check sites match the canonical SITES list",
    "event-type": "events and diagnosis rules use live EventType members",
    "rpc-parity": "client .call() names and server handlers agree 1:1",
    "durable-write": "job-dir artifacts go through utils/durable, not "
                     "bare open/os.replace",
    "clock": "time.time() never feeds deadline/duration arithmetic",
    "span-leak": "started spans are context-managed or .end()ed",
    "thread-leak": "threads are daemonized or joined",
    "lock-blocking": "no blocking calls while holding coordinator/fleet "
                     "locks",
    "bare-except": "no bare except:",
    "defaults-md": "conf/defaults.md matches the key registry",
    "alert-registry": "default alert-pack series resolve in "
                      "metrics.SERIES and every shipped rule is "
                      "exercised by a test",
}
# v2 protocol rules (devtools/protocol.py): the coordinator↔executor
# directive/journal/fence/beacon/terminal/metrics contracts, both sides.
RULES.update(RULES_V2)
# guarded-by rules (devtools/race.py): the static half of the race
# detector — GUARDED_BY-declared fields only touched under their lock,
# and no undeclared shared-field stores on instrumented classes.
RULES.update(RULES_RACE)

_SUPPRESS_RE = re.compile(r"tony:\s*lint-ignore\[([a-z\-]+)\]")
_KEY_TOKEN_RE = re.compile(
    r"tony\.[a-z][a-z0-9_\-]*(?:\.[a-z0-9_\-]+)*")
#: dotted tokens whose last segment is one of these are file names
#: ("job.tony.json", "tony.xml"), not config-key references
_FILE_EXTS = ("xml", "json", "jsonl", "yaml", "yml", "md", "py", "log",
              "prom", "addr", "pgid")
_RPC_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(?:\.[a-z][a-z0-9_]*)?$")

#: job-dir artifact files whose torn read changes a control-flow decision
#: (lease adoption, recovery, verified restore): writes must be durable.
#: Matched as substrings of the unparsed path expression, so both the
#: literal basename and the module-level *_FILE constant naming it hit.
_ARTIFACTS = (
    "ready.json", "lease.json", "adopted.json", "pool-exit.json",
    "pool.addr", "tony-final.json", "session.journal", "incident.json",
    "metrics.counters", "tony-manifest", ".tony-localized",
    "perf.json", "profile-request.json",
    "fleet.addr", "fleet.journal", "fleet.status", "fleet.counters",
    "fleet.incident", "health.cordon",
    "READY_FILE", "LEASE_FILE", "ADOPTED_FILE", "POOL_EXIT_FILE",
    "POOL_ADDR_FILE", "FINAL_CONFIG_FILE", "JOURNAL_FILE",
    "INCIDENT_FILE", "METRICS_COUNTERS_FILE", "MANIFEST_NAME",
    "MANIFEST_FILE", "addr_file", "PERF_FILE", "PROFILE_REQUEST_FILE",
    "FLEET_ADDR_FILE", "FLEET_JOURNAL_FILE", "FLEET_STATUS_FILE",
    "FLEET_COUNTERS_FILE", "FLEET_INCIDENT_FILE", "FLEET_CORDON_FILE",
)

#: attribute names whose call blocks (or can block) the calling thread —
#: forbidden while a coordinator/session lock is held (rule lock-blocking)
_BLOCKING_ATTRS = {
    "sleep", "wait", "join", "call", "fsync", "sendall", "recv",
    "connect", "communicate", "check_call", "check_output", "run_job",
}
_BLOCKING_NAMES = {"fsync_file", "fsync_dir", "atomic_write",
                   "durable_replace", "sleep"}


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    file: str          # repo-relative path
    line: int
    message: str

    def to_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)

    def __str__(self) -> str:
        return f"{self.file}:{self.line}: [{self.rule}] {self.message}"


class _Src:
    """One parsed source file."""

    def __init__(self, path: str, rel: str) -> None:
        self.path = path
        self.rel = rel
        with open(path, "r", encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=rel)
        self._parents: Optional[Dict[ast.AST, ast.AST]] = None

    def parent_map(self) -> Dict[ast.AST, ast.AST]:
        if self._parents is None:
            self._parents = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    self._parents[child] = node
        return self._parents

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        parents = self.parent_map()
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = parents.get(cur)
        return None


def _is_call_to(node: ast.AST, obj: str, attrs: Iterable[str]) -> bool:
    """Is ``node`` a Call of ``obj.attr(...)`` for attr in attrs?"""
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in set(attrs)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == obj)


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _contains_time_time(node: ast.AST) -> Optional[int]:
    """Line of a ``time.time()`` call anywhere under ``node``, else None."""
    for sub in ast.walk(node):
        if _is_call_to(sub, "time", ("time",)):
            return sub.lineno
    return None


class Linter:
    def __init__(self, repo_root: Optional[str] = None) -> None:
        if repo_root is None:
            repo_root = os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))
        self.root = repo_root
        self.pkg = os.path.join(repo_root, "tony_tpu")
        self.tests = os.path.join(repo_root, "tests")
        self.findings: List[Finding] = []
        self.suppressed: List[Finding] = []

    # -- plumbing --------------------------------------------------------
    def _py_files(self, base: str) -> List[str]:
        out = []
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            out.extend(os.path.join(dirpath, f) for f in filenames
                       if f.endswith(".py"))
        return sorted(out)

    def _sources(self, base: str) -> List[_Src]:
        out = []
        for path in self._py_files(base):
            rel = os.path.relpath(path, self.root)
            try:
                out.append(_Src(path, rel))
            except SyntaxError as e:
                self._emit("conf-key", rel, e.lineno or 1,
                           f"file does not parse: {e.msg}", src=None)
        return out

    def _emit(self, rule: str, rel: str, line: int, message: str,
              src: Optional[_Src]) -> None:
        f = Finding(rule, rel, line, message)
        if src is not None and 1 <= line <= len(src.lines):
            m = _SUPPRESS_RE.search(src.lines[line - 1])
            if m and m.group(1) == rule:
                self.suppressed.append(f)
                return
        self.findings.append(f)

    # -- entry point -----------------------------------------------------
    def run(self, rules: Optional[Sequence[str]] = None) -> List[Finding]:
        active = set(rules) if rules else set(RULES)
        unknown = active - set(RULES)
        if unknown:
            raise ValueError(f"unknown lint rule(s) {sorted(unknown)}; "
                             f"known: {sorted(RULES)}")
        pkg_srcs = self._sources(self.pkg)
        per_file = {
            "conf-key": self._check_conf_keys,
            "event-type": self._check_event_types,
            "durable-write": self._check_durable_writes,
            "clock": self._check_clock,
            "span-leak": self._check_span_leak,
            "thread-leak": self._check_thread_leak,
            "lock-blocking": self._check_lock_blocking,
            "bare-except": self._check_bare_except,
        }
        for src in pkg_srcs:
            for rule, fn in per_file.items():
                if rule in active:
                    fn(src)
        if "fault-site" in active:
            self._check_fault_sites(pkg_srcs)
        if "alert-registry" in active:
            self._check_alert_registry(pkg_srcs)
        if "rpc-parity" in active:
            self._check_rpc_parity(pkg_srcs)
        if "defaults-md" in active:
            self._check_defaults_md()
        run_protocol_rules(self, pkg_srcs, active)
        run_race_rules(self, pkg_srcs, active)
        self.findings.sort(key=lambda f: (f.file, f.line, f.rule))
        return self.findings

    # -- conf-key --------------------------------------------------------
    def _check_conf_keys(self, src: _Src) -> None:
        if src.rel.endswith(os.path.join("conf", "keys.py")):
            return
        from tony_tpu.conf import keys as K

        registered = set(K.registry())
        for node in ast.walk(src.tree):
            text = _const_str(node)
            if text is None or "tony." not in text:
                continue
            for tok in _KEY_TOKEN_RE.findall(text):
                if tok in registered or K.parse_job_key(tok):
                    continue
                if tok.rsplit(".", 1)[-1] in _FILE_EXTS:
                    continue    # "job.tony.json": a file name, not a key
                # prose mention of a key family ("tony.fault.<site>",
                # "tony.application.security.tls-*")
                if any(k.startswith(tok + ".") for k in registered):
                    continue
                if tok.endswith("-") and any(
                        k.startswith(tok) for k in registered):
                    continue
                self._emit(
                    "conf-key", src.rel, node.lineno,
                    f"string references {tok!r}, which is not a "
                    f"registered ConfigKey (conf/keys.py), a dynamic "
                    f"per-jobtype key, or a registered key family", src)

    # -- fault-site ------------------------------------------------------
    def _check_fault_sites(self, srcs: List[_Src]) -> None:
        from tony_tpu import faults

        listed = set(faults.SITES)
        used: Dict[str, Tuple[str, int]] = {}
        faults_rel = None
        for src in srcs:
            if src.rel.endswith(os.path.join("tony_tpu", "faults.py")):
                faults_rel = src
                continue
            for node in ast.walk(src.tree):
                if not _is_call_to(node, "faults",
                                   ("fire", "check", "fire_amount",
                                    "check_partition")):
                    continue
                site = _const_str(node.args[0]) if node.args else None
                if site is None:
                    self._emit("fault-site", src.rel, node.lineno,
                               "fault site must be a string literal so "
                               "the call site is statically checkable",
                               src)
                    continue
                used.setdefault(site, (src.rel, node.lineno))
                if site not in listed:
                    self._emit(
                        "fault-site", src.rel, node.lineno,
                        f"fault site {site!r} is not in faults.SITES "
                        f"(canonical list; add it there + a conf key)",
                        src)
        sites_line = 1
        if faults_rel is not None:
            for node in ast.walk(faults_rel.tree):
                if isinstance(node, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == "SITES"
                        for t in node.targets):
                    sites_line = node.lineno
                    break
        for site in sorted(listed - set(used)):
            self._emit(
                "fault-site",
                faults_rel.rel if faults_rel else "tony_tpu/faults.py",
                sites_line,
                f"fault site {site!r} is listed in faults.SITES but has "
                f"no fire/check call site — dead site or missed wiring",
                faults_rel)

    # -- alert-registry --------------------------------------------------
    def _check_alert_registry(self, srcs: List[_Src]) -> None:
        """Both directions of the default alert-pack contract: every
        metric family a shipped rule evaluates must be a registered
        ``metrics.SERIES`` entry (an alert over a family nobody emits
        can never fire), and every shipped rule name must appear as a
        string literal in some test (a rule nobody exercises is a
        paging policy with no proof)."""
        from tony_tpu import metrics as M
        from tony_tpu.alerts import rules as AR

        pack = list(AR.default_job_pack()) + list(AR.default_fleet_pack())
        rules_src = None
        for src in srcs:
            if src.rel.endswith(os.path.join("alerts", "rules.py")):
                rules_src = src
                break
        rules_rel = (rules_src.rel if rules_src
                     else os.path.join("tony_tpu", "alerts", "rules.py"))

        def _literal_line(text: str) -> int:
            if rules_src is not None:
                for node in ast.walk(rules_src.tree):
                    if _const_str(node) == text:
                        return node.lineno
            return 1

        for rule in pack:
            if rule.series not in M.SERIES:
                self._emit(
                    "alert-registry", rules_rel,
                    _literal_line(rule.series),
                    f"default alert rule {rule.name!r} evaluates metric "
                    f"family {rule.series!r}, which is not registered in "
                    f"metrics.SERIES — it can never fire", rules_src)
        tests_dir = os.path.join(self.root, "tests")
        if not os.path.isdir(tests_dir):
            self._emit(
                "alert-registry", rules_rel, 1,
                "tests/ directory not found — cannot prove the default "
                "alert pack is exercised by tests", rules_src)
            return
        names = {r.name for r in pack}
        referenced: Set[str] = set()
        for src in self._sources(tests_dir):
            for node in ast.walk(src.tree):
                text = _const_str(node)
                if text is not None and text in names:
                    referenced.add(text)
            if referenced == names:
                break
        for rule in pack:
            if rule.name not in referenced:
                self._emit(
                    "alert-registry", rules_rel,
                    _literal_line(rule.name),
                    f"default alert rule {rule.name!r} is not referenced "
                    f"by any test under tests/ — every shipped rule must "
                    f"be exercised", rules_src)

    # -- event-type ------------------------------------------------------
    def _check_event_types(self, src: _Src) -> None:
        if src.rel.endswith(os.path.join("events", "events.py")):
            return
        from tony_tpu.events.events import EventType

        members = {e.name for e in EventType}

        def _check_name(name: str, line: int, what: str) -> None:
            if name not in members:
                self._emit(
                    "event-type", src.rel, line,
                    f"{what} references EventType member {name!r}, which "
                    f"does not exist (events/events.py)", src)

        in_rules = src.rel.endswith(os.path.join("diagnosis", "rules.py"))
        for node in ast.walk(src.tree):
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "EventType"):
                _check_name(node.attr, node.lineno, "attribute access")
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "Event" and node.args):
                first = node.args[0]
                ok = ((isinstance(first, ast.Attribute)
                       and isinstance(first.value, ast.Name)
                       and first.value.id == "EventType")
                      or (isinstance(first, ast.Call)
                          and isinstance(first.func, ast.Name)
                          and first.func.id == "EventType"))
                if not ok:
                    self._emit(
                        "event-type", src.rel, node.lineno,
                        "Event(...) must be constructed with an EventType "
                        "member (no raw strings/variables — the registry "
                        "is the contract)", src)
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "events_of" and node.args):
                s = _const_str(node.args[0])
                if s is not None:
                    _check_name(s, node.lineno, "events_of()")
            if (in_rules and isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "_rule"):
                tup = None
                if len(node.args) >= 3:
                    tup = node.args[2]
                for kw in node.keywords:
                    if kw.arg == "events_used":
                        tup = kw.value
                if isinstance(tup, ast.Tuple):
                    for el in tup.elts:
                        s = _const_str(el)
                        if s is not None:
                            _check_name(s, el.lineno,
                                        "rule events_used")

    # -- rpc-parity ------------------------------------------------------
    def _check_rpc_parity(self, srcs: List[_Src]) -> None:
        handlers: Dict[str, Tuple[str, int, _Src]] = {}
        for src in srcs:
            service_classes: Set[str] = set()
            for node in ast.walk(src.tree):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id == "RpcServer" and node.args):
                    first = node.args[0]
                    if (isinstance(first, ast.Call)
                            and isinstance(first.func, ast.Name)):
                        service_classes.add(first.func.id)
                    elif isinstance(first, ast.Name):
                        service_classes.add(first.id)
            if not service_classes:
                continue
            for node in ast.walk(src.tree):
                if (isinstance(node, ast.ClassDef)
                        and node.name in service_classes):
                    for item in node.body:
                        if not isinstance(item, (ast.FunctionDef,
                                                 ast.AsyncFunctionDef)):
                            continue
                        if item.name.startswith("_"):
                            continue
                        rpc_name = item.name.replace("__", ".")
                        handlers[rpc_name] = (src.rel, item.lineno, src)

        callers: Dict[str, Tuple[str, int, _Src]] = {}
        caller_srcs = list(srcs)
        if os.path.isdir(self.tests):
            caller_srcs += self._sources(self.tests)
        for src in caller_srcs:
            for node in ast.walk(src.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "call" and node.args):
                    continue
                if (isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "subprocess"):
                    continue
                name = _const_str(node.args[0])
                if name is None or not _RPC_NAME_RE.match(name):
                    continue
                callers.setdefault(name, (src.rel, node.lineno, src))
                if (name not in handlers
                        and src.rel.startswith("tony_tpu")):
                    self._emit(
                        "rpc-parity", src.rel, node.lineno,
                        f"client calls RPC method {name!r}, but no "
                        f"registered server handler defines it", src)
        for name, (rel, line, hsrc) in sorted(handlers.items()):
            if name not in callers:
                self._emit(
                    "rpc-parity", rel, line,
                    f"RPC handler {name!r} has no call site in the "
                    f"package or tests — dead surface (delete it, or "
                    f"cover it)", hsrc)

    # -- durable-write ---------------------------------------------------
    def _check_durable_writes(self, src: _Src) -> None:
        if src.rel.endswith(os.path.join("utils", "durable.py")):
            return
        for node in ast.walk(src.tree):
            if _is_call_to(node, "os", ("replace",)):
                self._emit(
                    "durable-write", src.rel, node.lineno,
                    "hand-rolled os.replace: a rename is only durable "
                    "after file+dir fsync — use utils.durable "
                    "atomic_write / durable_replace / fsync_path", src)
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "open"
                    and len(node.args) >= 2):
                mode = _const_str(node.args[1])
                if mode is None or "w" not in mode:
                    continue
                target = ast.unparse(node.args[0])
                hit = next((a for a in _ARTIFACTS if a in target), None)
                if hit is not None:
                    self._emit(
                        "durable-write", src.rel, node.lineno,
                        f"bare open(..., {mode!r}) targets job-dir "
                        f"artifact {hit!r}: a torn write could be "
                        f"adopted as valid state — use "
                        f"utils.durable.atomic_write", src)

    # -- clock -----------------------------------------------------------
    def _check_clock(self, src: _Src) -> None:
        flagged: Set[int] = set()
        for node in ast.walk(src.tree):
            line: Optional[int] = None
            if isinstance(node, ast.BinOp) and isinstance(
                    node.op, (ast.Add, ast.Sub)):
                line = (_contains_time_time(node.left)
                        or _contains_time_time(node.right))
            elif isinstance(node, ast.Compare):
                line = _contains_time_time(node.left)
                for cmp_ in node.comparators:
                    line = line or _contains_time_time(cmp_)
            if line is not None and line not in flagged:
                flagged.add(line)
                self._emit(
                    "clock", src.rel, line,
                    "time.time() feeds deadline/duration arithmetic — an "
                    "NTP step skews it; use time.monotonic() (wall time "
                    "is for timestamp anchors only)", src)

    # -- span-leak -------------------------------------------------------
    def _check_span_leak(self, src: _Src) -> None:
        attr_ends: Set[str] = set()
        for node in ast.walk(src.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "end"
                    and isinstance(node.func.value, ast.Attribute)):
                attr_ends.add(node.func.value.attr)
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and isinstance(node.value.func, ast.Attribute)
                    and node.value.func.attr == "start_span"):
                continue
            if len(node.targets) != 1:
                continue
            target = node.targets[0]
            if isinstance(target, ast.Subscript):
                continue    # tracked collections have their own lifecycle
            if isinstance(target, ast.Attribute):
                if target.attr not in attr_ends:
                    self._emit(
                        "span-leak", src.rel, node.lineno,
                        f"span stored on .{target.attr} is never "
                        f".end()ed in this module — it will report as "
                        f"unclosed in the trace export", src)
                continue
            if not isinstance(target, ast.Name):
                continue
            fn = src.enclosing_function(node)
            scope = fn if fn is not None else src.tree
            closed = False
            for sub in ast.walk(scope):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "end"
                        and isinstance(sub.func.value, ast.Name)
                        and sub.func.value.id == target.id):
                    closed = True
                if (isinstance(sub, ast.withitem)
                        and sub.context_expr is node.value):
                    closed = True
            if not closed:
                self._emit(
                    "span-leak", src.rel, node.lineno,
                    f"span {target.id!r} is started but never .end()ed "
                    f"in the enclosing function (use `with` or end it "
                    f"on every path)", src)

    # -- thread-leak -----------------------------------------------------
    def _check_thread_leak(self, src: _Src) -> None:
        for node in ast.walk(src.tree):
            is_thread = (_is_call_to(node, "threading", ("Thread",))
                         or (isinstance(node, ast.Call)
                             and isinstance(node.func, ast.Name)
                             and node.func.id == "Thread"))
            if not is_thread:
                continue
            daemon = False
            for kw in node.keywords:
                if (kw.arg == "daemon"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value is True):
                    daemon = True
            if daemon:
                continue
            fn = src.enclosing_function(node)
            scope = fn if fn is not None else src.tree
            handled = False
            for sub in ast.walk(scope):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "join"):
                    handled = True
                if (isinstance(sub, ast.Assign)
                        and any(isinstance(t, ast.Attribute)
                                and t.attr == "daemon"
                                for t in sub.targets)):
                    handled = True
            if not handled:
                self._emit(
                    "thread-leak", src.rel, node.lineno,
                    "thread is neither daemon=True nor joined in the "
                    "constructing function — it can outlive teardown "
                    "and wedge interpreter exit", src)

    # -- lock-blocking ---------------------------------------------------
    def _check_lock_blocking(self, src: _Src) -> None:
        # Control-plane scope: the coordinator AND the fleet daemon both
        # hold locks that RPC handlers and monitor/scheduler ticks
        # contend for (thread-leak needs no such extension — it already
        # sweeps the whole package).
        if not any((os.sep + d + os.sep) in src.rel
                   for d in ("coordinator", "fleet")):
            return
        lock_attrs: Set[str] = set()
        for node in ast.walk(src.tree):
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and _is_call_to(node.value, "threading",
                                    ("Lock", "RLock"))):
                for t in node.targets:
                    if isinstance(t, ast.Attribute):
                        lock_attrs.add(t.attr)
        if not lock_attrs:
            return
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.With):
                continue
            held = any(
                isinstance(item.context_expr, ast.Attribute)
                and item.context_expr.attr in lock_attrs
                for item in node.items)
            if not held:
                continue
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    if not isinstance(sub, ast.Call):
                        continue
                    name = None
                    if (isinstance(sub.func, ast.Attribute)
                            and sub.func.attr in _BLOCKING_ATTRS):
                        name = sub.func.attr
                        if name == "join" and not self._is_thread_join(sub):
                            name = None
                    elif (isinstance(sub.func, ast.Name)
                          and sub.func.id in _BLOCKING_NAMES):
                        name = sub.func.id
                    elif (isinstance(sub.func, ast.Attribute)
                          and isinstance(sub.func.value, ast.Name)
                          and sub.func.value.id == "subprocess"):
                        name = f"subprocess.{sub.func.attr}"
                    if name is not None:
                        self._emit(
                            "lock-blocking", src.rel, sub.lineno,
                            f"blocking call {name!r} while holding a "
                            f"coordinator lock: every RPC handler and "
                            f"monitor tick behind that lock stalls with "
                            f"it — move the blocking work outside the "
                            f"critical section", src)

    @staticmethod
    def _is_thread_join(call: ast.Call) -> bool:
        """Distinguish Thread.join([timeout]) from str.join(iterable) and
        os.path.join(a, b, ...): thread joins take zero args or one
        numeric/keyword timeout; the others take string/iterable args."""
        assert isinstance(call.func, ast.Attribute)
        if isinstance(call.func.value, ast.Constant):
            return False        # ", ".join(...)
        if (isinstance(call.func.value, ast.Attribute)
                and call.func.value.attr == "path") or (
                isinstance(call.func.value, ast.Name)
                and call.func.value.id in ("os", "path", "posixpath")):
            return False        # os.path.join(...)
        if len(call.args) > 1:
            return False
        if call.args and isinstance(call.args[0], ast.Constant) \
                and isinstance(call.args[0].value, str):
            return False
        return True

    # -- bare-except -----------------------------------------------------
    def _check_bare_except(self, src: _Src) -> None:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                self._emit(
                    "bare-except", src.rel, node.lineno,
                    "bare except: swallows SystemExit/KeyboardInterrupt "
                    "and every bug — name the exceptions you mean", src)

    # -- defaults-md -----------------------------------------------------
    def _check_defaults_md(self) -> None:
        from tony_tpu.conf import keys as K

        path = os.path.join(self.pkg, "conf", "defaults.md")
        rel = os.path.relpath(path, self.root)
        try:
            with open(path, "r", encoding="utf-8") as f:
                on_disk = f.read()
        except OSError:
            self._emit("defaults-md", rel, 1,
                       "conf/defaults.md is missing — run "
                       "`python -m tony_tpu.conf.keys`", None)
            return
        if on_disk != K.defaults_markdown():
            self._emit("defaults-md", rel, 1,
                       "conf/defaults.md is stale against the key "
                       "registry — run `python -m tony_tpu.conf.keys`",
                       None)


def run_lint(repo_root: Optional[str] = None,
             rules: Optional[Sequence[str]] = None
             ) -> Tuple[List[Finding], List[Finding]]:
    """Run the lint; returns (findings, suppressed)."""
    linter = Linter(repo_root)
    linter.run(rules)
    return linter.findings, linter.suppressed


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="tony-tpu lint",
        description="Project invariant checker (see docs/development.md).")
    p.add_argument("--root", default=None,
                   help="repo root (default: the installed package's)")
    p.add_argument("--rule", action="append", default=None,
                   help="run only this rule (repeatable)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit findings as a JSON array")
    p.add_argument("--list", action="store_true",
                   help="list rule ids and exit")
    args = p.parse_args(argv)
    if args.list:
        for rule, desc in RULES.items():
            print(f"{rule:14s} {desc}")
        return 0
    findings, suppressed = run_lint(args.root, args.rule)
    if args.as_json:
        print(json.dumps({
            "findings": [f.to_dict() for f in findings],
            "suppressed": [f.to_dict() for f in suppressed],
        }, indent=1, sort_keys=True))
    else:
        for f in findings:
            print(f)
        if suppressed:
            print(f"({len(suppressed)} suppressed via lint-ignore)",
                  file=sys.stderr)
        if findings:
            print(f"{len(findings)} finding(s)", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
