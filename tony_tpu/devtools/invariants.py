"""Cross-artifact trace invariant checker: the runtime half of tonycheck.

tonylint's protocol rules (devtools/protocol.py) prove the CODE keeps
both halves of each control-plane contract; this module proves a
finished RUN did. It reads a job dir's artifacts — the write-ahead
journal, the span log, perf.json, metrics.prom — and asserts the
invariants the protocol promises at runtime:

=======================  ==================================================
journal-gen-monotonic    coordinator generations strictly increase
journal-mgen-monotonic   membership generations never step backwards
journal-resize-dangling  every REC_RESIZE ``start`` is closed by an
                         ``applied`` (same-or-newer mgen), a superseding
                         ``start``, or an epoch reset — never left open
journal-migrate-dangling every REC_MIGRATE ``start`` is closed by an
                         ``applied`` (same-or-newer mgen), a
                         ``superseded`` record (host loss folded the op
                         into the elastic ladder), or an epoch reset —
                         a SUCCEEDED job never ends mid-migration
journal-migrate-mgen-monotonic
                         migration records respect the shared
                         membership-generation fence — no stale-slice
                         migration frame lands after a newer mgen
journal-stale-epoch      no sessioned record lands after a newer epoch
                         fence (a stale frame was accepted post-fence)
journal-terminal         no REC_TASK transition out of SUCCEEDED/FAILED/
                         KILLED and no REC_REGISTER for a terminal task
                         within an epoch (applied resizes reset their
                         job's fold — the journaled absorb path)
trace-unclosed           every opened span is closed (single-generation
                         runs; pre-recovery lives may leave unclosed
                         spans and are reported as a note instead)
trace-orphan-close       no span close without a matching open
trace-parent             every span's parent resolves inside the log
phase-sum                perf.json per-phase seconds sum to the
                         attributed wall within tolerance
metrics-unregistered     every ``tony_*`` family in metrics.prom is in
                         ``tony_tpu.metrics.SERIES``
fleet-gen-monotonic      fleet daemon generations strictly increase
fleet-unknown-job        no grant/preempt/migrate/state record for a
                         job the journal never saw submitted
fleet-double-grant       no second grant for a job without an
                         intervening terminal state or daemon
                         generation bump (a recovered daemon may
                         re-carry a grant out; a live one must not)
fleet-terminal           no job state transition out of FINISHED/
                         FAILED/CANCELLED
fleet-capacity           granted hosts never exceed the journaled pool
                         (slices × hosts-per-slice) at any point
fleet-decision           every REC_FLEET_DECISION names a journaled
                         submission, never lands after the job's
                         terminal state, and reason transitions are
                         deduplicated (no two consecutive identical
                         holds for one job within a daemon life —
                         the bounded-journal contract)
fleet-ledger             the goodput ledger re-folded offline books
                         non-negative phases that sum to each
                         terminal job's wall within 1% (the PR 9
                         sum-to-wall discipline at the fleet layer;
                         migration wall books under its own phase and
                         participates in the same sum)
fleet-sim-parity         the journaled grant/preempt sequence re-derives
                         bit-for-bit through the real policy engine
                         (fleet/simulator.py parity replay) — placements
                         and shrink victims the engine would not have
                         planned mean daemon/policy drift, the condition
                         under which `fleet whatif` counterfactuals stop
                         being trustworthy (hold-reason wording and
                         operator migrations are notes, not violations;
                         non-terminal journals are skipped)
fleet-trace-stitch       every granted job's span tree carries the
                         fleet's trace id (the grant's injected
                         tony.internal.fleet-trace-id reached the
                         client) so one --fleet export stitches
health-quarantine-evidence
                         every non-manual REC_FLEET_HEALTH quarantine
                         carries attributed-failure evidence (the
                         score/probe/slice trail that justified the
                         cordon) — a quarantine the journal cannot
                         explain is an unauditable cordon
health-dangling-cordon   every manual (operator) cordon is closed by
                         an uncordon before the journal ends — manual
                         cordons never auto-expire, so a dangling one
                         is capacity silently lost
alert-journal            REC_ALERT / REC_FLEET_ALERT transitions carry
                         valid states and are dedup-fenced per (rule,
                         state) — never re-journaled per tick — and a
                         SUCCEEDED job's journal never ends with a
                         rule still firing (the teardown resolve);
                         failure paths keep the firing record as
                         diagnosis evidence (note, not violation)
=======================  ==================================================

Surfaces: ``tony-tpu check <app|job_dir>`` (and the no-deps module CLI
``python -m tony_tpu.devtools.invariants <job_dir>``), plus the autouse
pytest fixture in tests/conftest.py that verifies the artifact dir of
every e2e and virtual-gang drill at teardown — every existing slow drill
is a protocol test for free.

Stdlib only (the journal/tracing readers it leans on are stdlib too), so
CI runs it without installing anything. Torn tails are tolerated exactly
as the readers tolerate them (write-ahead discipline makes the prefix
the truth) and reported as notes, never violations.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from tony_tpu import constants
from tony_tpu.coordinator import journal as journal_mod

_TERMINAL = ("SUCCEEDED", "FAILED", "KILLED")

#: perf.json sum-to-wall tolerance: the writer rounds each phase to 4
#: decimals, so allow 1% relative plus a rounding epsilon.
PHASE_SUM_REL_TOL = 0.01
PHASE_SUM_ABS_TOL = 0.05


@dataclasses.dataclass(frozen=True)
class Violation:
    """One invariant breach, in the diagnosis evidence style: what broke,
    where (artifact + record/line number), and the record that proves
    it."""

    rule: str
    artifact: str
    record: int          # 1-based record/line index; 0 = file-level
    message: str
    evidence: str = ""

    def to_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)

    def __str__(self) -> str:
        s = f"{self.artifact}:{self.record}: [{self.rule}] {self.message}"
        if self.evidence:
            s += f"\n    evidence: {self.evidence}"
        return s


@dataclasses.dataclass
class Report:
    job_dir: str
    violations: List[Violation] = dataclasses.field(default_factory=list)
    notes: List[str] = dataclasses.field(default_factory=list)
    checked: Dict[str, int] = dataclasses.field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.violations

    def to_dict(self) -> Dict[str, object]:
        return {
            "job_dir": self.job_dir,
            "ok": self.ok,
            "violations": [v.to_dict() for v in self.violations],
            "notes": list(self.notes),
            "checked": dict(self.checked),
        }


def _iter_journal_records(
        path: str) -> Tuple[List[Tuple[int, Dict[str, Any]]], bool]:
    """(index, record) for every decodable complete record; mirrors
    replay()'s torn-tail posture. Returns (records, torn)."""
    lines, torn = journal_mod._iter_complete_lines(path)
    out: List[Tuple[int, Dict[str, Any]]] = []
    for i, raw in enumerate(lines, start=1):
        raw = raw.strip()
        if not raw:
            continue
        try:
            rec = json.loads(raw.decode("utf-8"))
            if not isinstance(rec, dict):
                raise ValueError("not an object")
        except (ValueError, UnicodeDecodeError):
            torn = True
            break
        out.append((i, rec))
    return out, torn


# ---------------------------------------------------------------------------
# journal invariants
# ---------------------------------------------------------------------------
def _check_journal(path: str, rel: str, rep: Report,
                   strict: bool) -> Tuple[int, bool]:
    """All journal invariants in one ordered fold. Returns
    ``(generations, clean)`` — the recovery count and whether the run
    was disturbance-free (one epoch, no failed/killed task): the facts
    the span-tree check needs to know how much stitching to demand.
    ``strict`` = the job finished SUCCEEDED: end-state invariants (no
    dangling resize) are hard; on failure paths they degrade to notes."""
    records, torn = _iter_journal_records(path)
    rep.checked[rel] = len(records)
    clean = True
    if torn:
        rep.notes.append(
            f"{rel}: torn/undecodable tail after {len(records)} good "
            f"record(s) — the crash window; prefix checked")
    last_gen: Optional[int] = None
    n_gens = 0
    max_mgen: Optional[int] = None
    session: Optional[int] = None
    # job → (record_idx, mgen) of the open resize start
    open_start: Dict[str, Tuple[int, int]] = {}
    # job → (record_idx, mgen, target) of the open migration start
    open_migrate: Dict[str, Tuple[int, int, str]] = {}
    # task → folded status for the current epoch
    tasks: Dict[str, str] = {}
    # alert rule → (record_idx, last journaled state). Deliberately NOT
    # cleared on REC_EPOCH: alerts watch the job across retry epochs
    # (mirror replay()).
    alert_state: Dict[str, Tuple[int, str]] = {}
    for idx, rec in records:
        t = rec.get("t")
        ev = json.dumps(rec, sort_keys=True)
        if t == journal_mod.REC_GENERATION:
            n_gens += 1
            gen = int(rec.get("generation", 0) or 0)
            if last_gen is not None and gen <= last_gen:
                rep.violations.append(Violation(
                    "journal-gen-monotonic", rel, idx,
                    f"coordinator generation {gen} does not supersede "
                    f"{last_gen} — generations must strictly increase "
                    f"(the split-brain fence)", ev))
            last_gen = max(gen, last_gen or 0)
        elif t == journal_mod.REC_EPOCH:
            new_session = int(rec.get("session", 0) or 0)
            if session is not None and new_session < session:
                rep.violations.append(Violation(
                    "journal-stale-epoch", rel, idx,
                    f"epoch record steps back from session {session} to "
                    f"{new_session}", ev))
            if new_session > 0:
                clean = False      # a retry epoch happened
            session = new_session
            tasks.clear()
            open_start.clear()     # an epoch reset abandons the resize
            open_migrate.clear()   # ... and the in-flight migration
        elif t == journal_mod.REC_RESIZE:
            if _stale_session(rec, session):
                rep.violations.append(_stale_violation(rel, idx, rec,
                                                       session, ev))
                continue
            job = str(rec.get("job", "") or "")
            mgen = int(rec.get("mgen", 0) or 0)
            if max_mgen is not None and mgen < max_mgen:
                rep.violations.append(Violation(
                    "journal-mgen-monotonic", rel, idx,
                    f"membership generation {mgen} steps back from "
                    f"{max_mgen} — a stale-topology record landed after "
                    f"the fence", ev))
            max_mgen = max(mgen, max_mgen if max_mgen is not None else 0)
            if rec.get("phase") == "applied":
                start = open_start.pop(job, None)
                if start is not None and mgen < start[1]:
                    rep.violations.append(Violation(
                        "journal-resize-dangling", rel, idx,
                        f"resize applied at mgen {mgen} but the open "
                        f"start is newer (mgen {start[1]}) — the applied "
                        f"topology is stale", ev))
                # The applied topology supersedes the member tasks' fold:
                # replaced indices relaunch fresh (the journaled absorb
                # path) — mirror replay() and reset the job's fold.
                for tid in [tid for tid in tasks
                            if tid.partition(":")[0] == job]:
                    del tasks[tid]
            else:
                open_start[job] = (idx, mgen)
        elif t == journal_mod.REC_MIGRATE:
            if _stale_session(rec, session):
                rep.violations.append(_stale_violation(rel, idx, rec,
                                                       session, ev))
                continue
            job = str(rec.get("job", "") or "")
            mgen = int(rec.get("mgen", 0) or 0)
            target = str(rec.get("target", "") or "")
            if max_mgen is not None and mgen < max_mgen:
                rep.violations.append(Violation(
                    "journal-migrate-mgen-monotonic", rel, idx,
                    f"migration record at mgen {mgen} steps back from "
                    f"{max_mgen} — a stale-slice migration frame landed "
                    f"after the membership fence", ev))
            max_mgen = max(mgen, max_mgen if max_mgen is not None else 0)
            phase = rec.get("phase")
            if phase == "applied":
                start = open_migrate.pop(job, None)
                if start is not None and mgen < start[1]:
                    rep.violations.append(Violation(
                        "journal-migrate-dangling", rel, idx,
                        f"migration applied at mgen {mgen} but the open "
                        f"start is newer (mgen {start[1]}) — the "
                        f"applied move is stale", ev))
                # Every member relaunched on the target slice: the
                # source gang's fold is superseded exactly like an
                # applied resize (mirror replay()). The killed source
                # executors also strand their spans — this run no
                # longer owes a fully stitched tree.
                for tid in [tid for tid in tasks
                            if tid.partition(":")[0] == job]:
                    del tasks[tid]
                clean = False
            elif phase == "superseded":
                # A host loss mid-migration folded the op into the
                # ordinary elastic ladder: the start is closed, the
                # REC_RESIZE that follows carries the story on.
                open_migrate.pop(job, None)
            else:
                open_migrate[job] = (idx, mgen, target)
        elif t == journal_mod.REC_ALERT:
            rule = str(rec.get("rule", "") or "")
            state = str(rec.get("state", "") or "")
            if state not in ("pending", "firing", "resolved"):
                rep.violations.append(Violation(
                    "alert-journal", rel, idx,
                    f"alert record for rule {rule!r} carries unknown "
                    f"state {state!r} — only pending/firing/resolved "
                    f"are journaled transitions", ev))
            elif alert_state.get(rule, (0, ""))[1] == state:
                rep.violations.append(Violation(
                    "alert-journal", rel, idx,
                    f"consecutive identical alert state {state!r} for "
                    f"rule {rule!r} — transitions must be dedup-fenced "
                    f"per (rule, state), never re-journaled per tick "
                    f"(the bounded-journal contract)", ev))
            alert_state[rule] = (idx, state)
        elif t in (journal_mod.REC_REGISTER, journal_mod.REC_TASK,
                   journal_mod.REC_PROGRESS, journal_mod.REC_VERDICT,
                   journal_mod.REC_JOB_SCHEDULED,
                   journal_mod.REC_JOB_COMPLETED):
            if _stale_session(rec, session):
                rep.violations.append(_stale_violation(rel, idx, rec,
                                                       session, ev))
                continue
            tid = str(rec.get("task", "") or "")
            if t == journal_mod.REC_TASK and tid:
                status = str(rec.get("status", "") or "")
                if status in ("FAILED", "KILLED"):
                    clean = False  # a task died along the way
                prev = tasks.get(tid)
                if prev in _TERMINAL and status != prev:
                    rep.violations.append(Violation(
                        "journal-terminal", rel, idx,
                        f"task {tid} transitions {prev} → {status} after "
                        f"a terminal state — a closed task identity was "
                        f"resurrected outside the journaled epoch-reset/"
                        f"absorb paths", ev))
                tasks[tid] = status
            elif t == journal_mod.REC_REGISTER and tid:
                if tasks.get(tid) in _TERMINAL:
                    rep.violations.append(Violation(
                        "journal-terminal", rel, idx,
                        f"register record for task {tid} in terminal "
                        f"state {tasks[tid]} — a registration frame was "
                        f"accepted after the task finished", ev))
    for job, (idx, mgen) in sorted(open_start.items()):
        msg = (f"resize start for job {job!r} (mgen {mgen}) is never "
               f"applied, superseded, or reset — the journal ends with "
               f"the resize in flight (a --recover would re-enter the "
               f"drain; a SUCCEEDED job must not end here)")
        if strict:
            rep.violations.append(Violation(
                "journal-resize-dangling", rel, idx, msg))
        else:
            # A job that died/was killed mid-resize legitimately leaves
            # the start open — that IS the recover re-entry record.
            rep.notes.append(f"{rel}:{idx}: {msg}")
    for job, (idx, mgen, target) in sorted(open_migrate.items()):
        msg = (f"migration start for job {job!r} (mgen {mgen}, target "
               f"{target!r}) is never applied, superseded, or reset — "
               f"the journal ends mid-migration (a --recover re-enters "
               f"the op; a SUCCEEDED job must not end here)")
        if strict:
            rep.violations.append(Violation(
                "journal-migrate-dangling", rel, idx, msg))
        else:
            # A coordinator killed mid-migration legitimately leaves
            # the start open — that IS the recover re-entry record.
            rep.notes.append(f"{rel}:{idx}: {msg}")
    for rule, (idx, state) in sorted(alert_state.items()):
        if state != "firing":
            continue
        msg = (f"alert rule {rule!r} is still firing when the journal "
               f"ends — a SUCCEEDED teardown resolves every alert "
               f"(resolve_all); on a failure path the firing record is "
               f"the diagnosis evidence")
        if strict:
            rep.violations.append(Violation(
                "alert-journal", rel, idx, msg))
        else:
            rep.notes.append(f"{rel}:{idx}: {msg}")
    return n_gens, clean and n_gens <= 1


def _stale_session(rec: Dict[str, Any], session: Optional[int]) -> bool:
    if session is None or "session" not in rec:
        return False
    try:
        return int(rec.get("session", 0) or 0) != session
    except (TypeError, ValueError):
        return True


def _stale_violation(rel: str, idx: int, rec: Dict[str, Any],
                     session: Optional[int], ev: str) -> Violation:
    return Violation(
        "journal-stale-epoch", rel, idx,
        f"record for session {rec.get('session')} appended while the "
        f"epoch fence is at session {session} — a stale-epoch frame was "
        f"accepted after the fence", ev)


# ---------------------------------------------------------------------------
# span-log invariants
# ---------------------------------------------------------------------------
def _check_spans(path: str, rel: str, rep: Report,
                 strict: bool) -> None:
    """``strict`` = SUCCEEDED + single generation + no task deaths/retry
    epochs: the only shape that owes a fully closed, fully stitched
    span tree (buffered tracers ship spans complete-only, so any kill
    along the way legitimately drops parents)."""
    from tony_tpu import tracing

    records = tracing.load_records(path)
    rep.checked[rel] = len(records)
    opens: Dict[str, Tuple[int, str]] = {}     # span id → (line, name)
    known: Set[str] = set()
    parents: List[Tuple[int, str, str]] = []   # (line, span name, parent)
    for i, recd in enumerate(records, start=1):
        ev = recd.get("ev")
        span = str(recd.get("span", "") or "")
        name = str(recd.get("name", "") or "")
        if ev == "B":
            opens[span] = (i, name)
            known.add(span)
        elif ev == "E":
            if opens.pop(span, None) is None:
                rep.violations.append(Violation(
                    "trace-orphan-close", rel, i,
                    f"span close for {span!r} has no matching open — "
                    f"the span tree is inconsistent",
                    json.dumps(recd, sort_keys=True)))
        elif ev in ("X", "I"):
            known.add(span)
        if ev in ("B", "X", "I"):
            parent = str(recd.get("parent", "") or "")
            if parent:
                parents.append((i, name, parent))
    if opens:
        names = ", ".join(
            f"{name} (line {line})"
            for line, name in sorted(opens.values())[:5])
        if strict:
            line = min(l for l, _ in opens.values())
            rep.violations.append(Violation(
                "trace-unclosed", rel, line,
                f"{len(opens)} span(s) opened but never closed on a "
                f"clean SUCCEEDED run: {names}"))
        else:
            # A SIGKILLed coordinator life (pre-recovery, or a crash
            # drill that never recovered) leaves its open spans
            # unclosed by design — evidence of what was in flight, not
            # a protocol breach.
            rep.notes.append(
                f"{rel}: {len(opens)} unclosed span(s) from a killed/"
                f"pre-recovery coordinator life: {names}")
    unresolved = [(i, name, p) for i, name, p in parents if p not in known]
    if not strict and unresolved:
        # Executor/client spans ship over best-effort trace.push, and a
        # buffered tracer only ships CLOSED spans: any task or
        # coordinator killed mid-life strands its children's parent
        # links. Only a clean single-epoch SUCCEEDED run owes a fully
        # stitched tree.
        rep.notes.append(
            f"{rel}: {len(unresolved)} unresolved parent link(s) on a "
            f"disturbed run (best-effort span push)")
        return
    for i, name, p in unresolved[:5]:
        rep.violations.append(Violation(
            "trace-parent", rel, i,
            f"span {name!r} has parent {p!r} which resolves to no span "
            f"in the log — the trace tree is broken at this edge"))
    if len(unresolved) > 5:
        rep.notes.append(f"{rel}: {len(unresolved) - 5} further "
                         f"unresolved parent link(s) suppressed")


# ---------------------------------------------------------------------------
# fleet-journal invariants (tony_tpu/fleet/journal.py)
# ---------------------------------------------------------------------------
def _check_fleet_journal(path: str, rel: str, rep: Report) -> None:
    """The fleet scheduler's write-ahead journal holds the multi-job
    half of the protocol: monotonic daemon generations, every grant for
    a known submission, at most one live grant per job per daemon life,
    terminal job states that stay terminal, and host accounting that
    never exceeds the journaled pool."""
    from tony_tpu.fleet import journal as fj

    records, torn = _iter_journal_records(path)
    rep.checked[rel] = len(records)
    if torn:
        rep.notes.append(
            f"{rel}: torn/undecodable tail after {len(records)} good "
            f"record(s) — the crash window; prefix checked")
    last_gen: Optional[int] = None
    capacity = 0
    submitted: Set[str] = set()
    # job → current state fold ("QUEUED"/"GRANTED"/lifecycle states)
    states: Dict[str, str] = {}
    hosts: Dict[str, int] = {}        # granted hosts per live job
    # job → (action, reason) of its last decision record this life —
    # the fleet-decision dedup fence (reset at fgen: a recovered daemon
    # legitimately re-records the holds it re-derives).
    last_decision: Dict[str, Tuple[str, str]] = {}
    # host → record index of a still-open manual cordon (fhealth
    # records replay across daemon lives — last-wins per host — so the
    # fold deliberately survives fgen bumps).
    open_manual: Dict[str, int] = {}
    # alert rule → last journaled state. Survives fgen bumps like the
    # health fold (a recovered daemon seeds its engine from the replay,
    # so the dedup fence carries across lives); a fleet journal MAY end
    # firing — the daemon is long-lived, there is no SUCCEEDED teardown.
    falert_state: Dict[str, str] = {}
    for idx, rec in records:
        t = rec.get("t")
        ev = json.dumps(rec, sort_keys=True)
        job = str(rec.get("job", "") or "")
        if t == fj.REC_FLEET_ALERT:
            rule = str(rec.get("rule", "") or "")
            state = str(rec.get("state", "") or "")
            if state not in ("pending", "firing", "resolved"):
                rep.violations.append(Violation(
                    "alert-journal", rel, idx,
                    f"fleet alert record for rule {rule!r} carries "
                    f"unknown state {state!r} — only pending/firing/"
                    f"resolved are journaled transitions", ev))
            elif falert_state.get(rule) == state:
                rep.violations.append(Violation(
                    "alert-journal", rel, idx,
                    f"consecutive identical alert state {state!r} for "
                    f"fleet rule {rule!r} — transitions must be "
                    f"dedup-fenced per (rule, state), never "
                    f"re-journaled per tick", ev))
            falert_state[rule] = state
            continue
        if t == fj.REC_FLEET_HEALTH:
            host = str(rec.get("host", "") or "")
            state = str(rec.get("state", "") or "")
            if state == "quarantined":
                if rec.get("manual"):
                    open_manual[host] = idx
                else:
                    open_manual.pop(host, None)
                    if not rec.get("evidence"):
                        rep.violations.append(Violation(
                            "health-quarantine-evidence", rel, idx,
                            f"quarantine of host {host} carries no "
                            f"attributed-failure evidence — the cordon "
                            f"cannot be audited", ev))
            else:
                # healthy (uncordon / clean canary) or probation both
                # close a manual-cordon episode.
                open_manual.pop(host, None)
            continue
        if t == fj.REC_FLEET_GEN:
            gen = int(rec.get("generation", 0) or 0)
            if last_gen is not None and gen <= last_gen:
                rep.violations.append(Violation(
                    "fleet-gen-monotonic", rel, idx,
                    f"fleet generation {gen} does not supersede "
                    f"{last_gen} — generations must strictly increase "
                    f"(the zombie-daemon fence)", ev))
            last_gen = max(gen, last_gen or 0)
            capacity = (int(rec.get("slices", 0) or 0)
                        * int(rec.get("hosts_per_slice", 0) or 0))
            # A new daemon life re-carries interrupted grants out: its
            # grant folds restart (the fgen record is the license), and
            # a granted-but-never-spawned job's hosts were never truly
            # in use — drop them from the capacity fold.
            for j, st in list(states.items()):
                if st == "GRANTED":
                    states[j] = "QUEUED"
                    hosts.pop(j, None)
            last_decision.clear()
            continue
        if t == fj.REC_FLEET_SUBMIT:
            submitted.add(job)
            states[job] = "QUEUED"
            continue
        if t not in (fj.REC_FLEET_GRANT, fj.REC_FLEET_PREEMPT,
                     fj.REC_FLEET_STATE, fj.REC_FLEET_DECISION,
                     fj.REC_FLEET_MIGRATE):
            continue
        if job not in submitted:
            rep.violations.append(Violation(
                "fleet-unknown-job", rel, idx,
                f"record for job {job!r} which the journal never saw "
                f"submitted — a grant/state without a submission", ev))
            continue
        prev = states.get(job, "QUEUED")
        if t == fj.REC_FLEET_DECISION:
            action = str(rec.get("action", "") or "")
            reason = str(rec.get("reason", "") or "")
            if prev in fj.TERMINAL_STATES:
                rep.violations.append(Violation(
                    "fleet-decision", rel, idx,
                    f"decision record for job {job} in terminal state "
                    f"{prev} — the explainer recorded a hold for a "
                    f"finished job", ev))
            elif last_decision.get(job) == (action, reason):
                rep.violations.append(Violation(
                    "fleet-decision", rel, idx,
                    f"consecutive identical decision for job {job} "
                    f"([{action}] {reason[:80]!r}) — decisions must be "
                    f"recorded per reason TRANSITION, never per tick "
                    f"(the bounded-journal contract)", ev))
            last_decision[job] = (action, reason)
            continue
        if t == fj.REC_FLEET_MIGRATE:
            # A live move re-books hosts between slices without
            # changing the count — the capacity fold is untouched; a
            # migration record for a finished job is still a breach.
            if prev in fj.TERMINAL_STATES:
                rep.violations.append(Violation(
                    "fleet-terminal", rel, idx,
                    f"migration record for job {job} in terminal state "
                    f"{prev} — a finished job was moved", ev))
            continue
        if t == fj.REC_FLEET_GRANT:
            # A grant closes the hold episode: the same hold may
            # legitimately recur after a preemption re-queues the job.
            last_decision.pop(job, None)
            if prev in fj.TERMINAL_STATES:
                rep.violations.append(Violation(
                    "fleet-terminal", rel, idx,
                    f"grant for job {job} in terminal state {prev} — a "
                    f"finished job was re-granted", ev))
            elif prev != "QUEUED":
                rep.violations.append(Violation(
                    "fleet-double-grant", rel, idx,
                    f"second grant for job {job} (state {prev}) with no "
                    f"intervening terminal state or generation bump — "
                    f"a duplicated grant runs the job twice", ev))
            states[job] = "GRANTED"
            hosts[job] = int(rec.get("hosts", 0) or 0)
        elif t == fj.REC_FLEET_PREEMPT:
            hosts[job] = int(rec.get("to", hosts.get(job, 0)) or 0)
        else:                        # REC_FLEET_STATE
            st = str(rec.get("state", "") or "")
            if prev in fj.TERMINAL_STATES and st != prev:
                rep.violations.append(Violation(
                    "fleet-terminal", rel, idx,
                    f"job {job} transitions {prev} → {st} after a "
                    f"terminal state — a closed job was resurrected",
                    ev))
            states[job] = st if st != fj.STATE_RESTORED \
                else fj.STATE_RUNNING
            if st == fj.STATE_RESTORED:
                hosts[job] = int(rec.get("hosts", hosts.get(job, 0))
                                 or 0)
            if st in fj.TERMINAL_STATES:
                hosts.pop(job, None)
        in_use = sum(hosts.values())
        if capacity and in_use > capacity:
            rep.violations.append(Violation(
                "fleet-capacity", rel, idx,
                f"granted hosts total {in_use} exceeds the journaled "
                f"pool of {capacity} — the scheduler over-committed",
                ev))
    for host, idx in sorted(open_manual.items()):
        rep.violations.append(Violation(
            "health-dangling-cordon", rel, idx,
            f"manual cordon of host {host} is never closed by an "
            f"uncordon — manual cordons do not auto-expire, so this "
            f"host is capacity silently lost"))


def _check_fleet_ledger(fleet_dir: str, rep: Report) -> None:
    """Re-fold the goodput ledger offline (fleet/ledger.py) and hold
    its own invariant: every terminal job's phases are non-negative and
    sum to its wall within 1% — the acceptance discipline that makes
    the per-tenant goodput numbers trustworthy."""
    from tony_tpu.fleet import ledger as fledger

    try:
        folded = fledger.fold_fleet_dir(fleet_dir)
    except Exception as e:  # noqa: BLE001 — a broken fold IS the finding
        rep.violations.append(Violation(
            "fleet-ledger", constants.FLEET_JOURNAL_FILE, 0,
            f"goodput-ledger fold failed over this fleet dir: {e}"))
        return
    checked = 0
    for job_id, led in sorted(folded.get("jobs", {}).items()):
        wall = float(led.get("wall_s", 0.0) or 0.0)
        if led.get("provisional") or wall <= 0:
            continue            # live jobs have no terminal anchor
        checked += 1
        phases = led.get("phases_s") or {}
        negative = {p: v for p, v in phases.items() if float(v) < 0}
        if negative:
            rep.violations.append(Violation(
                "fleet-ledger", constants.FLEET_JOURNAL_FILE, 0,
                f"job {job_id}: negative ledger phase(s) {negative} — "
                f"the wall partition went inconsistent",
                json.dumps(phases, sort_keys=True)))
            continue
        err = fledger.sum_to_wall_error(led)
        if err:
            total = sum(float(v) for v in phases.values())
            rep.violations.append(Violation(
                "fleet-ledger", constants.FLEET_JOURNAL_FILE, 0,
                f"job {job_id}: ledger phases sum to {total:.4f}s but "
                f"the wall is {wall:.4f}s (off by {err:.4f}s beyond "
                f"tolerance) — phase accounting leaked or double-"
                f"booked", json.dumps(phases, sort_keys=True)))
    rep.checked["fleet-ledger"] = checked


def _check_fleet_parity(fleet_dir: str, rep: Report) -> None:
    """Re-derive the journal's grant/preempt sequence through the real
    policy engine (fleet/simulator.py parity replay) and hold it
    bit-for-bit: a placement or victim the engine would not have
    produced means the daemon and the policy drifted — the exact
    condition under which `fleet whatif` counterfactuals (and the
    recorded journal itself) stop being trustworthy. Hold-decision
    REASON WORDING may drift across daemon versions (and operator
    migrations are exogenous), so only grant/preempt divergence is a
    violation; everything else is a note."""
    from tony_tpu.fleet import simulator as fsim
    from tony_tpu.fleet import timeline as ftimeline

    try:
        tl = ftimeline.load(fleet_dir)
        par = fsim.parity_replay(tl)
    except Exception as e:  # noqa: BLE001 — a crashed replay IS the finding
        rep.violations.append(Violation(
            "fleet-sim-parity", constants.FLEET_JOURNAL_FILE, 0,
            f"parity replay crashed over this fleet dir: {e}"))
        return
    if not par.get("supported"):
        rep.notes.append(
            f"fleet-sim-parity: skipped — {par.get('reason', '?')}")
        return
    counts = par.get("counts") or {}
    rep.checked["fleet-sim-parity"] = \
        counts.get("grant", 0) + counts.get("preempt", 0)
    gated = {"grant", "preempt"}
    for m in par.get("mismatches") or []:
        if m.get("kind") in gated:
            rep.violations.append(Violation(
                "fleet-sim-parity", constants.FLEET_JOURNAL_FILE,
                int(m.get("index", 0)),
                f"record {m['index']}: journaled {m['kind']} diverges "
                f"from the policy engine's plan — recorded "
                f"{m['recorded']}; the engine planned {m['expected']}"))
    soft = sum(v for k, v in (par.get("mismatch_counts") or {}).items()
               if k not in gated)
    if soft:
        rep.notes.append(
            f"fleet-sim-parity: {soft} decision/restore record(s) "
            f"differ from the replayed plan (reason wording or "
            f"recovery-path drift — not gated)")


def _check_fleet_trace(fleet_dir: str, rep: Report) -> None:
    """Fleet span-log hygiene + cross-layer stitching: the fleet dir's
    own span log must be tree-consistent (non-strict: a killed daemon
    life's opens are closed by the recovering life), and every granted
    job's span tree must carry the FLEET's trace id — the proof the
    grant's injected trace context reached the client."""
    from tony_tpu import tracing
    from tony_tpu.fleet import journal as fj
    from tony_tpu.fleet import ledger as fledger

    trace_path = os.path.join(fleet_dir, constants.TRACE_FILE)
    if not os.path.exists(trace_path):
        rep.notes.append(f"{constants.TRACE_FILE}: absent — fleet "
                         f"trace checks skipped (pre-ledger fleet dir "
                         f"or tracing disabled)")
        return
    _check_spans(trace_path, constants.TRACE_FILE, rep, strict=False)
    fleet_trace = tracing.existing_trace_id(trace_path)
    if not fleet_trace:
        return
    try:
        st = fj.replay(os.path.join(fleet_dir,
                                    constants.FLEET_JOURNAL_FILE))
    except fj.FleetJournalError:
        return
    dirs = fledger.job_history_dirs(fleet_dir)
    stitched = 0
    for job_id, fold in sorted(st.jobs.items()):
        if not fold.granted_ms or not fold.app_id:
            continue
        job_dir = dirs.get(fold.app_id)
        if job_dir is None:
            continue
        job_trace_path = os.path.join(job_dir, constants.TRACE_FILE)
        if not os.path.exists(job_trace_path):
            rep.notes.append(
                f"{job_id} ({fold.app_id}): no span log — stitching "
                f"unverifiable (job tracing disabled?)")
            continue
        job_trace = tracing.existing_trace_id(job_trace_path)
        if job_trace and job_trace != fleet_trace:
            rep.violations.append(Violation(
                "fleet-trace-stitch", constants.TRACE_FILE, 0,
                f"job {job_id} ({fold.app_id}) traces under "
                f"{job_trace!r}, not the fleet's {fleet_trace!r} — the "
                f"grant's injected trace id never reached the client, "
                f"so a --fleet export cannot stitch this job",
                job_trace_path))
        else:
            stitched += 1
    rep.checked["fleet-trace-stitch"] = stitched


# ---------------------------------------------------------------------------
# perf.json + metrics.prom invariants
# ---------------------------------------------------------------------------
def _check_perf(path: str, rel: str, rep: Report) -> None:
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        rep.notes.append(f"{rel}: absent or torn — skipped")
        return
    if not isinstance(doc, dict):
        return
    phases = doc.get("phases_s")
    wall = doc.get("wall_s")
    if not isinstance(phases, dict) or not isinstance(wall, (int, float)):
        return
    rep.checked[rel] = 1
    total = 0.0
    for v in phases.values():
        try:
            total += float(v)
        except (TypeError, ValueError):
            continue
    tol = max(PHASE_SUM_ABS_TOL, PHASE_SUM_REL_TOL * float(wall))
    if abs(total - float(wall)) > tol:
        rep.violations.append(Violation(
            "phase-sum", rel, 0,
            f"per-phase seconds sum to {total:.4f} but the attributed "
            f"wall is {wall:.4f} (tolerance {tol:.4f}) — phase "
            f"accounting leaked or double-booked step time",
            json.dumps({"phases_s": phases, "wall_s": wall},
                       sort_keys=True)))


def _check_prom(path: str, rel: str, rep: Report) -> None:
    from tony_tpu.metrics import SERIES

    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        rep.notes.append(f"{rel}: absent — skipped")
        return
    families = 0
    for lineno, line in enumerate(text.splitlines(), start=1):
        if not line.startswith("# TYPE "):
            continue
        parts = line.split()
        if len(parts) < 3:
            continue
        name = parts[2]
        if not name.startswith("tony_"):
            continue
        families += 1
        if name not in SERIES:
            rep.violations.append(Violation(
                "metrics-unregistered", rel, lineno,
                f"exported family {name!r} is not registered in "
                f"tony_tpu.metrics.SERIES — the registry and the "
                f"exposition drifted", line))
    rep.checked[rel] = families


def _finished_succeeded(job_dir: str) -> bool:
    """Did this job finalize SUCCEEDED? (From the jhist filename, the
    same source the history index uses.) Unknown/unfinished → False:
    the checker then holds only the always-invariants."""
    from tony_tpu.events import history

    path = history.find_history_file(job_dir)
    if not path:
        return False
    meta = history.parse_metadata(os.path.basename(path))
    return bool(meta is not None and meta.status == "SUCCEEDED")


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------
def check_job_dir(job_dir: str) -> Report:
    """Verify one job dir's artifacts. Absent artifacts are notes (a
    minimal job writes only the journal); present artifacts must hold
    their invariants. A FLEET dir (holds a fleet journal, usually no
    session journal) is checked by the fleet rules and its per-job
    artifacts skipped as absent."""
    rep = Report(job_dir=job_dir)
    fleet_path = os.path.join(job_dir, constants.FLEET_JOURNAL_FILE)
    if os.path.exists(fleet_path):
        _check_fleet_journal(fleet_path, constants.FLEET_JOURNAL_FILE,
                             rep)
        _check_prom(os.path.join(job_dir, constants.FLEET_PROM_FILE),
                    constants.FLEET_PROM_FILE, rep)
        _check_fleet_ledger(job_dir, rep)
        _check_fleet_parity(job_dir, rep)
        _check_fleet_trace(job_dir, rep)
        if not os.path.exists(os.path.join(job_dir,
                                           constants.JOURNAL_FILE)):
            return rep
    strict = _finished_succeeded(job_dir)
    if not strict:
        rep.notes.append(
            "job did not finish SUCCEEDED — end-state invariants "
            "(dangling resize, span-tree stitching) degrade to notes")
    journal_path = os.path.join(job_dir, constants.JOURNAL_FILE)
    clean = False
    if os.path.exists(journal_path):
        _, clean = _check_journal(journal_path, constants.JOURNAL_FILE,
                                  rep, strict)
    else:
        rep.notes.append(f"{constants.JOURNAL_FILE}: absent — journal "
                         f"checks skipped (journal disabled?)")
    trace_path = os.path.join(job_dir, constants.TRACE_FILE)
    if os.path.exists(trace_path):
        _check_spans(trace_path, constants.TRACE_FILE, rep,
                     strict=strict and clean)
    else:
        rep.notes.append(f"{constants.TRACE_FILE}: absent — span checks "
                         f"skipped (tracing disabled?)")
    _check_perf(os.path.join(job_dir, constants.PERF_FILE),
                constants.PERF_FILE, rep)
    _check_prom(os.path.join(job_dir, constants.METRICS_PROM_FILE),
                constants.METRICS_PROM_FILE, rep)
    return rep


def find_job_dirs(root: str) -> List[str]:
    """Every dir under ``root`` holding a session journal OR a fleet
    journal — how the pytest artifact fixture and `check` on a history
    root find the dirs to verify (a fleet drill's tmp_path holds both
    kinds, and every one is checked)."""
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        if constants.JOURNAL_FILE in filenames \
                or constants.FLEET_JOURNAL_FILE in filenames:
            out.append(dirpath)
    return sorted(out)


def check_tree(root: str) -> List[Report]:
    return [check_job_dir(d) for d in find_job_dirs(root)]


def render_text(reports: Sequence[Report]) -> str:
    lines: List[str] = []
    for rep in reports:
        head = "OK" if rep.ok else f"{len(rep.violations)} violation(s)"
        lines.append(f"{rep.job_dir}: {head}")
        for v in rep.violations:
            lines.append(f"  {v}")
        for n in rep.notes:
            lines.append(f"  note: {n}")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="tony-tpu check",
        description="Cross-artifact trace invariant checker "
                    "(see docs/development.md).")
    p.add_argument("target",
                   help="a job dir, or a tree of job dirs to scan")
    p.add_argument("--json", action="store_true", dest="as_json")
    args = p.parse_args(argv)
    if not os.path.isdir(args.target):
        print(f"not a directory: {args.target}", file=sys.stderr)
        return 2
    if os.path.exists(os.path.join(args.target, constants.JOURNAL_FILE)) \
            or os.path.exists(os.path.join(args.target,
                                           constants.FLEET_JOURNAL_FILE)):
        reports = [check_job_dir(args.target)]
    else:
        reports = check_tree(args.target)
        if not reports:
            print(f"no job/fleet dirs (no {constants.JOURNAL_FILE} or "
                  f"{constants.FLEET_JOURNAL_FILE}) under "
                  f"{args.target}", file=sys.stderr)
            return 2
    if args.as_json:
        print(json.dumps([r.to_dict() for r in reports], indent=1,
                         sort_keys=True))
    else:
        print(render_text(reports))
    return 0 if all(r.ok for r in reports) else 1


if __name__ == "__main__":
    sys.exit(main())
