"""User-process-side accelerator telemetry reporter.

The executor's TaskMonitor samples process-tree RSS fine, but HBM belongs
to the *user* process — the one that initialized the TPU runtime — so a
monitor-side ``jax.local_devices()`` always reads 0 (round-1 VERDICT weak
#7; the reference has the same split: ``TaskMonitor.java`` samples inside
the container alongside the training process, :109-170).

Mechanism: the executor exports ``TONY_METRICS_FILE`` into the user
process's environment; importing ``tony_tpu`` there auto-starts a daemon
thread (``maybe_start``) that periodically writes device stats to that file
via atomic replace. The TaskMonitor tails the file and merges the values
into the metrics it pushes — so TASK_FINISHED events carry real HBM
numbers without the user writing a line of code. Scripts that never import
``tony_tpu`` simply keep RSS-only metrics (never an error).

The reporter NEVER imports jax itself: it only reads stats once the user's
own code has brought the runtime up (jax present in sys.modules), so a
non-JAX task doesn't get a TPU runtime forced into it.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import threading
import time
from typing import Dict, Optional

from tony_tpu import constants

_started = threading.Lock()
_thread: Optional[threading.Thread] = None

# ---------------------------------------------------------------------------
# Step-time utilization (VERDICT r3 #8; reference samples GPU duty cycle via
# nvidia-smi, TaskMonitor.java:116-170 + GpuDiscoverer.java:88-131 — on TPU
# there is no device-side util counter to shell out to, so the signal is
# derived from the training loop itself: wrap each step in
# ``with telemetry.step(flops=...)`` and the reporter publishes steps/s,
# duty cycle, and — when FLOPs are declared and the device kind has a known
# peak — MFU).
# ---------------------------------------------------------------------------
_step_lock = threading.Lock()
_steps = {"count": 0, "busy_s": 0.0, "flops": 0.0, "tokens": 0.0,
          "first_start": 0.0, "last_end": 0.0, "first_end_wall": 0.0}

# Public peak bf16 matmul FLOP/s per chip (spec sheets), for the MFU derive.
PEAK_BF16_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def step_done(started_at: float, flops: float = 0.0,
              tokens: float = 0.0) -> None:
    """Record one completed training step that began at ``started_at``
    (``time.monotonic()``). Prefer the ``step()`` context manager."""
    from tony_tpu import faults

    if faults.fire("user.hang"):
        # Injected user hang: the recording is silently dropped, so the
        # published step counter freezes while the process (and its
        # executor's heartbeats) keep running — exactly the shape the
        # coordinator's progress-based liveness must catch.
        return
    delay = faults.fire_amount("user.slow_step")
    if delay:
        # Injected straggler skew: stretch this step by the configured
        # amount BEFORE timestamping, so the slowdown lands in the step
        # rate the gang-median policing compares.
        time.sleep(delay)
    now = time.monotonic()
    with _step_lock:
        if not _steps["first_start"]:
            _steps["first_start"] = started_at
            # Wall-clock completion of the FIRST step: the one absolute
            # timestamp the executor's first-step trace span (and the
            # bench's submit→first-step metric) anchors on.
            _steps["first_end_wall"] = time.time()
        _steps["count"] += 1
        _steps["busy_s"] += max(0.0, now - started_at)
        _steps["flops"] += flops
        _steps["tokens"] += tokens
        _steps["last_end"] = now


@contextlib.contextmanager
def step(flops: float = 0.0, tokens: float = 0.0):
    """Time one training step: ``with telemetry.step(flops=6*params*B*S):``.
    Feeds steps/s, duty-cycle, and MFU into the task's metrics stream."""
    t0 = time.monotonic()
    try:
        yield
    finally:
        step_done(t0, flops=flops, tokens=tokens)


def step_stats() -> Dict[str, float]:
    """Derived utilization over the window since the first recorded step;
    {} until a step completes."""
    with _step_lock:
        s = dict(_steps)
    if not s["count"]:
        return {}
    wall = max(s["last_end"] - s["first_start"], 1e-9)
    out = {
        "steps_completed": float(s["count"]),
        "steps_per_sec": s["count"] / wall,
        "mean_step_s": s["busy_s"] / s["count"],
        # Fraction of wall time spent inside steps: the duty-cycle proxy
        # (host-side; dispatch gaps and eval/checkpoint pauses count as
        # idle, which is exactly the signal an operator wants).
        "step_duty_cycle": min(1.0, s["busy_s"] / wall),
    }
    if s["tokens"]:
        out["tokens_per_sec"] = s["tokens"] / wall
    if s["flops"]:
        out["model_flops_per_sec"] = s["flops"] / wall
    if s["first_end_wall"]:
        out["first_step_done_ts"] = s["first_end_wall"]
    return out


def collect_device_stats() -> Dict[str, float]:
    """Best-effort per-process accelerator + step stats; {} when neither is
    available. Step stats publish WITHOUT a jax runtime — a PyTorch or
    plain-Python loop wrapped in telemetry.step() still feeds the progress
    beacon the coordinator's hang detection watches (device stats alone
    stay jax-gated: this module never imports jax itself)."""
    out: Dict[str, float] = {}
    per_device: list = []
    jax = None
    if "jax" in sys.modules:
        try:
            jax = sys.modules["jax"]
            devices = jax.local_devices()
        except Exception:  # noqa: BLE001 — telemetry must never break the task
            jax, devices = None, []
        if jax is not None:
            out["device_count"] = float(len(devices))
            in_use = peak = 0.0
            for d in devices:
                try:
                    stats = d.memory_stats() or {}
                except Exception:  # noqa: BLE001
                    stats = {}
                b = float(stats.get("bytes_in_use", 0) or 0)
                p = float(stats.get("peak_bytes_in_use", b) or b)
                in_use += b
                peak += p
                per_device.append({"kind": getattr(d, "device_kind", "?"),
                                   "bytes_in_use": b,
                                   "peak_bytes_in_use": p})
            out["hbm_bytes_in_use"] = in_use
            out["hbm_peak_bytes"] = peak
            out["devices"] = per_device  # type: ignore[assignment]
    util = step_stats()
    if util:
        out.update(util)
        kind = per_device[0]["kind"] if per_device else ""
        peak_fl = next((v for k, v in PEAK_BF16_FLOPS.items()
                        if str(kind).startswith(k)), None)
        if jax is not None and peak_fl \
                and util.get("model_flops_per_sec"):
            # flops passed to step() are the model's GLOBAL per-step FLOPs
            # (the 6·N·B·S convention over the global batch), so the
            # denominator must be the GLOBAL device pool — local devices
            # alone would overstate MFU by process_count on multi-host
            # slices.
            try:
                n_global = jax.device_count()
            except Exception:  # noqa: BLE001
                n_global = len(per_device) or 1
            out["mfu_vs_peak_bf16"] = (util["model_flops_per_sec"]
                                       / (peak_fl * n_global))
    return out


def write_stats_once(path: str) -> bool:
    stats = collect_device_stats()
    if not stats:
        return False
    stats["ts"] = time.time()
    stats["pid"] = os.getpid()
    try:
        from tony_tpu.utils.durable import atomic_write

        atomic_write(path, json.dumps(stats).encode("utf-8"))
        return True
    except OSError:
        return False


def _loop(path: str, interval_s: float) -> None:
    while True:
        write_stats_once(path)
        time.sleep(interval_s)


def maybe_start(interval_s: float = 3.0) -> bool:
    """Start the reporter iff TONY_METRICS_FILE is set and it isn't running
    yet. Called from tony_tpu/__init__ — a bare import inside a task is
    enough to light up HBM telemetry. ``TONY_TELEMETRY_INTERVAL_S``
    overrides the cadence (progress-liveness tests tighten it so step
    counters publish faster than the progress deadline)."""
    global _thread
    path = os.environ.get(constants.METRICS_FILE, "")
    if not path:
        return False
    try:
        interval_s = float(
            os.environ.get(constants.TELEMETRY_INTERVAL_ENV, "")
            or interval_s)
    except ValueError:
        pass
    with _started:
        if _thread is not None and _thread.is_alive():
            return True
        _thread = threading.Thread(target=_loop, args=(path, interval_s),
                                   name="tony-telemetry", daemon=True)
        _thread.start()
        return True


def read_stats(path: str) -> Dict[str, float]:
    """Monitor side: read the latest reporter snapshot ({} if absent)."""
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


# ---------------------------------------------------------------------------
# Hung-task diagnostics: pre-registered all-thread stack dump.
#
# When the coordinator declares a task HUNG (progress frozen, heartbeats
# alive — coordinator/liveness.py) the executor signals the USER process
# group with the signal it exported as TONY_STACKDUMP_SIGNAL. This handler
# — registered at `import tony_tpu`, i.e. before the user code can wedge —
# makes that signal dump every thread's stack to stderr (the task log),
# turning "it just stopped" postmortems into tracebacks.
# ---------------------------------------------------------------------------
_dump_registered = False


def install_stack_dump_handler(stream=None) -> bool:
    """Register a faulthandler all-thread stack dump on the signal named by
    ``TONY_STACKDUMP_SIGNAL`` (exported by the executor into the user
    env). No-op without the env var. A handler the user already installed
    on that signal is detected and warned about, never broken: the dump
    chains to it (both run). Returns True iff the dump handler is armed."""
    global _dump_registered
    spec = os.environ.get(constants.STACKDUMP_SIGNAL, "")
    if not spec:
        return False
    if _dump_registered:
        return True
    try:
        signum = int(spec)
    except ValueError:
        return False
    import faulthandler
    import logging
    import signal as _signal

    try:
        existing = _signal.getsignal(signum)
    except (ValueError, OSError):
        return False
    chain = callable(existing) and \
        existing is not _signal.default_int_handler
    if chain:
        # The user process got here with its own handler already on the
        # dump signal (framework or user code). Do not break it — chain —
        # but say so, because a handler that exits would still cut the
        # dump short. Chaining over SIG_DFL would instead re-run the
        # signal's DEFAULT action (terminate, for SIGUSR1/2) and kill the
        # process we are trying to diagnose — hence callable-only.
        logging.getLogger(__name__).warning(
            "signal %d already has a user handler (%r); chaining the "
            "tony-tpu stack-dump handler in front of it — hung-task "
            "dumps will run both", signum, existing)
    try:
        faulthandler.register(signum, file=stream or sys.stderr,
                              all_threads=True, chain=chain)
    except (ValueError, OSError, RuntimeError, AttributeError):
        # Non-main interpreter, closed stderr, or a platform without
        # faulthandler signals: diagnostics are best-effort, never fatal.
        return False
    _dump_registered = True
    return True
