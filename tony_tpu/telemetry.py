"""User-process-side accelerator telemetry reporter.

The executor's TaskMonitor samples process-tree RSS fine, but HBM belongs
to the *user* process — the one that initialized the TPU runtime — so a
monitor-side ``jax.local_devices()`` always reads 0 (round-1 VERDICT weak
#7; the reference has the same split: ``TaskMonitor.java`` samples inside
the container alongside the training process, :109-170).

Mechanism: the executor exports ``TONY_METRICS_FILE`` into the user
process's environment; importing ``tony_tpu`` there auto-starts a daemon
thread (``maybe_start``) that periodically writes device stats to that file
via atomic replace. The TaskMonitor tails the file and merges the values
into the metrics it pushes — so TASK_FINISHED events carry real HBM
numbers without the user writing a line of code. Scripts that never import
``tony_tpu`` simply keep RSS-only metrics (never an error).

The reporter NEVER imports jax itself: it only reads stats once the user's
own code has brought the runtime up (jax present in sys.modules), so a
non-JAX task doesn't get a TPU runtime forced into it.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Dict, Optional

from tony_tpu import constants

_started = threading.Lock()
_thread: Optional[threading.Thread] = None


def collect_device_stats() -> Dict[str, float]:
    """Best-effort per-process accelerator stats; {} when no runtime is up
    in this process."""
    if "jax" not in sys.modules:
        return {}
    try:
        jax = sys.modules["jax"]
        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — telemetry must never break the task
        return {}
    out: Dict[str, float] = {"device_count": float(len(devices))}
    in_use = peak = 0.0
    per_device = []
    for d in devices:
        try:
            stats = d.memory_stats() or {}
        except Exception:  # noqa: BLE001
            stats = {}
        b = float(stats.get("bytes_in_use", 0) or 0)
        p = float(stats.get("peak_bytes_in_use", b) or b)
        in_use += b
        peak += p
        per_device.append({"kind": getattr(d, "device_kind", "?"),
                           "bytes_in_use": b, "peak_bytes_in_use": p})
    out["hbm_bytes_in_use"] = in_use
    out["hbm_peak_bytes"] = peak
    out["devices"] = per_device  # type: ignore[assignment]
    return out


def write_stats_once(path: str) -> bool:
    stats = collect_device_stats()
    if not stats:
        return False
    stats["ts"] = time.time()
    stats["pid"] = os.getpid()
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(stats, f)
        os.replace(tmp, path)
        return True
    except OSError:
        return False


def _loop(path: str, interval_s: float) -> None:
    while True:
        write_stats_once(path)
        time.sleep(interval_s)


def maybe_start(interval_s: float = 3.0) -> bool:
    """Start the reporter iff TONY_METRICS_FILE is set and it isn't running
    yet. Called from tony_tpu/__init__ — a bare import inside a task is
    enough to light up HBM telemetry."""
    global _thread
    path = os.environ.get(constants.METRICS_FILE, "")
    if not path:
        return False
    with _started:
        if _thread is not None and _thread.is_alive():
            return True
        _thread = threading.Thread(target=_loop, args=(path, interval_s),
                                   name="tony-telemetry", daemon=True)
        _thread.start()
        return True


def read_stats(path: str) -> Dict[str, float]:
    """Monitor side: read the latest reporter snapshot ({} if absent)."""
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}
