"""User-process-side accelerator telemetry reporter.

The executor's TaskMonitor samples process-tree RSS fine, but HBM belongs
to the *user* process — the one that initialized the TPU runtime — so a
monitor-side ``jax.local_devices()`` always reads 0 (round-1 VERDICT weak
#7; the reference has the same split: ``TaskMonitor.java`` samples inside
the container alongside the training process, :109-170).

Mechanism: the executor exports ``TONY_METRICS_FILE`` into the user
process's environment; importing ``tony_tpu`` there auto-starts a daemon
thread (``maybe_start``) that periodically writes device stats to that file
via atomic replace. The TaskMonitor tails the file and merges the values
into the metrics it pushes — so TASK_FINISHED events carry real HBM
numbers without the user writing a line of code. Scripts that never import
``tony_tpu`` simply keep RSS-only metrics (never an error).

The reporter NEVER imports jax itself: it only reads stats once the user's
own code has brought the runtime up (jax present in sys.modules), so a
non-JAX task doesn't get a TPU runtime forced into it.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import sys
import threading
import time
from typing import Deque, Dict, Optional

from tony_tpu import constants

_started = threading.Lock()
_thread: Optional[threading.Thread] = None

# ---------------------------------------------------------------------------
# Step-time utilization (VERDICT r3 #8; reference samples GPU duty cycle via
# nvidia-smi, TaskMonitor.java:116-170 + GpuDiscoverer.java:88-131 — on TPU
# there is no device-side util counter to shell out to, so the signal is
# derived from the training loop itself: wrap each step in
# ``with telemetry.step(flops=...)`` and the reporter publishes steps/s,
# duty cycle, and — when FLOPs are declared and the device kind has a known
# peak — MFU).
# ---------------------------------------------------------------------------
_step_lock = threading.Lock()
_steps = {"count": 0, "busy_s": 0.0, "flops": 0.0, "tokens": 0.0,
          "first_start": 0.0, "last_end": 0.0, "first_end_wall": 0.0}

# Public peak bf16 matmul FLOP/s per chip (spec sheets), for the MFU derive.
PEAK_BF16_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


# ---------------------------------------------------------------------------
# Per-step PHASE accounting (steady-state step-time attribution).
#
# ``step()``/``step_stats()`` answer "how fast"; nothing answered "where
# does the step go". The Gemma-on-TPU comparison (PAPERS.md) is built on
# exactly this decomposition — input wait vs device compute vs collective
# vs checkpoint stall — so the ``phase(name)`` context manager times any
# slice of the training loop, and ``step_done`` folds the accumulated
# phase seconds into a ring of per-step records whose attribution
# interval runs from the PREVIOUS step's end to this step's end (so
# between-step work — the prefetch queue wait, a checkpoint save — is
# attributed to the step that paid for it).
#
# Three of the five canonical phases come free:
# - ``data_wait``: ShardedBatchIterator.__next__ (tony_tpu/data.py)
# - ``ckpt_stall``: CheckpointManager.save/wait (checkpoint/manager.py)
# - ``step_compute``: defaults to the step() busy time when no explicit
#   step_compute phase was recorded (``block_until_ready``-anchor it
#   yourself via ``with telemetry.phase("step_compute") as p: ...;
#   p.block_until_ready(loss)`` for dispatch-gap-free numbers).
# ``h2d``, ``comms`` and ``eval`` are one `with` statement each.
# Everything unattributed lands in the synthetic ``other`` bucket, so the
# per-step phases ALWAYS sum to the wall interval.
# ---------------------------------------------------------------------------
#: canonical phase names (free-form names are accepted; these are the
#: ones the bottleneck classifier (tony_tpu/profiling/verdict.py) reads).
PHASES = ("data_wait", "h2d", "step_compute", "comms", "ckpt_stall",
          "eval")
#: synthetic bucket: wall time no phase claimed (host-side gaps).
OTHER_PHASE = "other"

_phase_lock = threading.Lock()
_phase_acc: Dict[str, float] = {}   # seconds since the last step boundary
_phase_cum: Dict[str, float] = {}   # job-cumulative, folded per step
_phase_wall_cum = 0.0               # cumulative attribution wall
_phase_steps = 0
_phase_ring: Deque[dict] = collections.deque(
    maxlen=max(8, int(os.environ.get("TONY_PHASE_RING_STEPS", "") or 256)))


class _PhaseSpan:
    """Handle yielded by ``phase()``: ``block_until_ready(x)`` anchors the
    phase end on device completion (a dispatch-async step would otherwise
    time only the enqueue). No-op passthrough without a live jax."""

    @staticmethod
    def block_until_ready(x):
        jax = sys.modules.get("jax")
        if jax is not None:
            try:
                return jax.block_until_ready(x)
            except Exception:  # noqa: BLE001 — timing aid, never fatal
                return x
        return x


@contextlib.contextmanager
def phase(name: str):
    """Attribute the enclosed wall time to step-phase ``name``:
    ``with telemetry.phase("data_wait"): batch = next(it)``. Folded into
    the per-step ring at the next ``step_done`` and shipped on the
    heartbeat metrics beacon as ``tony_step_phase_seconds``."""
    t0 = time.monotonic()
    try:
        yield _PhaseSpan()
    finally:
        dt = time.monotonic() - t0
        with _phase_lock:
            _phase_acc[name] = _phase_acc.get(name, 0.0) + dt


def _fold_phases(interval_s: float, busy_s: float) -> None:
    """Close one attribution interval (step_done): drain the accumulator
    into the ring + cumulative totals, defaulting step_compute to the
    step's busy time and booking the unattributed remainder as other."""
    global _phase_wall_cum, _phase_steps
    with _phase_lock:
        acc = dict(_phase_acc)
        _phase_acc.clear()
        if "step_compute" not in acc:
            acc["step_compute"] = busy_s
        wall = max(interval_s, 0.0)
        attributed = sum(acc.values())
        if attributed > wall:
            # Overlapped phases (an async save timed across several
            # steps) can over-attribute; widen the wall rather than
            # invent a negative other bucket.
            wall = attributed
        acc[OTHER_PHASE] = wall - attributed
        for k, v in acc.items():
            _phase_cum[k] = _phase_cum.get(k, 0.0) + v
        _phase_wall_cum += wall
        _phase_steps += 1
        _phase_ring.append({"wall_s": wall, "phases": acc})


def phase_stats() -> Dict[str, object]:
    """Step-time attribution snapshot: cumulative seconds per phase (sum
    EXACTLY equals ``wall_s`` — ``other`` holds the unattributed rest)
    plus recent per-step means over the ring. {} before the first step."""
    with _phase_lock:
        if not _phase_steps:
            return {}
        out: Dict[str, object] = {
            "steps": float(_phase_steps),
            "wall_s": _phase_wall_cum,
            "cum": dict(_phase_cum),
        }
        n = len(_phase_ring)
        if n:
            recent: Dict[str, float] = {}
            rwall = 0.0
            for rec in _phase_ring:
                rwall += rec["wall_s"]
                for k, v in rec["phases"].items():
                    recent[k] = recent.get(k, 0.0) + v
            out["recent"] = {k: v / n for k, v in recent.items()}
            out["recent_wall_s"] = rwall / n
            out["recent_steps"] = float(n)
    return out


def _reset_phase_state() -> None:
    """Tests/bench probes: start attribution from a clean slate."""
    global _phase_wall_cum, _phase_steps
    with _phase_lock:
        _phase_acc.clear()
        _phase_cum.clear()
        _phase_wall_cum = 0.0
        _phase_steps = 0
        _phase_ring.clear()


def step_done(started_at: float, flops: float = 0.0,
              tokens: float = 0.0) -> None:
    """Record one completed training step that began at ``started_at``
    (``time.monotonic()``). Prefer the ``step()`` context manager."""
    from tony_tpu import faults

    if faults.fire("user.hang"):
        # Injected user hang: the recording is silently dropped, so the
        # published step counter freezes while the process (and its
        # executor's heartbeats) keep running — exactly the shape the
        # coordinator's progress-based liveness must catch.
        return
    delay = faults.fire_amount("user.slow_step")
    if delay:
        # Injected straggler skew: stretch this step by the configured
        # amount BEFORE timestamping, so the slowdown lands in the step
        # rate the gang-median policing compares.
        time.sleep(delay)
    now = time.monotonic()
    with _step_lock:
        if not _steps["first_start"]:
            _steps["first_start"] = started_at
            # Wall-clock completion of the FIRST step: the one absolute
            # timestamp the executor's first-step trace span (and the
            # bench's submit→first-step metric) anchors on.
            _steps["first_end_wall"] = time.time()
        prev_end = _steps["last_end"]
        busy = max(0.0, now - started_at)
        _steps["count"] += 1
        _steps["busy_s"] += busy
        _steps["flops"] += flops
        _steps["tokens"] += tokens
        _steps["last_end"] = now
    # Attribution interval: previous step end → this step end, so the
    # data wait / checkpoint stall BETWEEN steps lands on the step that
    # paid for it; the first step's interval is its own busy time
    # (compile/restore before it was never on the clock).
    _fold_phases(now - prev_end if prev_end else busy, busy)
    _profile_on_step_boundary()


@contextlib.contextmanager
def step(flops: float = 0.0, tokens: float = 0.0):
    """Time one training step: ``with telemetry.step(flops=6*params*B*S):``.
    Feeds steps/s, duty-cycle, and MFU into the task's metrics stream."""
    t0 = time.monotonic()
    try:
        yield
    finally:
        step_done(t0, flops=flops, tokens=tokens)


def step_stats() -> Dict[str, float]:
    """Derived utilization over the window since the first recorded step;
    {} until a step completes."""
    with _step_lock:
        s = dict(_steps)
    if not s["count"]:
        return {}
    wall = max(s["last_end"] - s["first_start"], 1e-9)
    out = {
        "steps_completed": float(s["count"]),
        "steps_per_sec": s["count"] / wall,
        "mean_step_s": s["busy_s"] / s["count"],
        # Fraction of wall time spent inside steps: the duty-cycle proxy
        # (host-side; dispatch gaps and eval/checkpoint pauses count as
        # idle, which is exactly the signal an operator wants).
        "step_duty_cycle": min(1.0, s["busy_s"] / wall),
    }
    if s["tokens"]:
        out["tokens_per_sec"] = s["tokens"] / wall
    if s["flops"]:
        out["model_flops_per_sec"] = s["flops"] / wall
    if s["first_end_wall"]:
        out["first_step_done_ts"] = s["first_end_wall"]
    return out


# ---------------------------------------------------------------------------
# On-demand device profiling (live, any task, mid-run).
#
# `tony-tpu profile <app>` turns the static chief-only trace_window()
# contract (tony_tpu/profiler.py: edit user code, decide before launch)
# into a live directive: the coordinator rides a PROFILE request on the
# heartbeat response, the executor writes it to the request file this
# module polls (TONY_PROFILE_REQUEST_FILE, reporter-loop cadence), and
# the NEXT step boundary arms ``jax.profiler`` for N steps — the capture
# brackets whole steps, never a half-dispatched one. The result (or the
# failure: fault site ``profile.capture``) rides the metrics file back
# onto the next beat. Capture must never kill or stall training: every
# failure shape degrades to a reported PROFILE_FAILED.
# ---------------------------------------------------------------------------
_profile_lock = threading.Lock()
_profile: Dict[str, object] = {
    "last_id": 0,        # highest request id ever seen (the dedup fence)
    "pending": None,     # request waiting for the next step boundary
    "active": None,      # {"req":..., "remaining": n} while tracing
    "result": None,      # last terminal {"id","status","dir"|"error",...}
}


def _poll_profile_request(path: str = "") -> None:
    """Reporter-loop tick: adopt a new profile request from the request
    file (executor-written, atomic replace). Dedup on the request id —
    the directive is re-sent every beat until the result lands."""
    path = path or os.environ.get(constants.PROFILE_REQUEST_ENV, "")
    if not path:
        return
    try:
        with open(path, encoding="utf-8") as f:
            req = json.load(f)
        req_id = int(req.get("id", 0))
    except (OSError, ValueError, TypeError):
        return
    if req_id <= 0:
        return
    with _profile_lock:
        if req_id <= int(_profile["last_id"]):  # type: ignore[arg-type]
            return
        _profile["last_id"] = req_id
        _profile["pending"] = {
            "id": req_id,
            "steps": max(1, int(req.get("steps", 1) or 1)),
            "dir": str(req.get("dir", "") or ""),
        }


def _profile_on_step_boundary() -> None:
    """step_done hook: start a pending capture at this step boundary, or
    advance/stop an active one. Never raises — a failed capture becomes a
    PROFILE_FAILED result on the beacon and the loop keeps training."""
    with _profile_lock:
        pending = _profile["pending"]
        active = _profile["active"]
    if active is not None:
        active["remaining"] -= 1
        if active["remaining"] > 0:
            return
        req = active["req"]
        result = {"id": req["id"], "steps": req["steps"]}
        try:
            sys.modules["jax"].profiler.stop_trace()
            result.update(status="captured", dir=req["dir"])
        except Exception as e:  # noqa: BLE001 — capture is best-effort
            result.update(status="failed", error=f"stop_trace: {e}"[:300])
        with _profile_lock:
            _profile["active"] = None
            _profile["result"] = result
        return
    if pending is None:
        return
    result = {"id": pending["id"], "steps": pending["steps"]}
    try:
        from tony_tpu import faults

        faults.check("profile.capture")
        jax = sys.modules.get("jax")
        if jax is None:
            raise RuntimeError("jax is not initialized in this process")
        dest = pending["dir"] or os.path.join(
            os.getcwd(), "profile", f"ondemand-{pending['id']}")
        try:
            os.makedirs(dest, exist_ok=True)
        except OSError:
            # Directive named a dir this host can't write (remote-host
            # task vs. coordinator job dir): capture locally and report
            # where the artifact actually is.
            dest = os.path.join(os.getcwd(), "profile",
                                f"ondemand-{pending['id']}")
            os.makedirs(dest, exist_ok=True)
        pending["dir"] = dest
        jax.profiler.start_trace(dest)
    except Exception as e:  # noqa: BLE001 — never stall training
        with _profile_lock:
            _profile["pending"] = None
            _profile["result"] = {**result, "status": "failed",
                                  "error": str(e)[:300]}
        return
    with _profile_lock:
        _profile["pending"] = None
        _profile["active"] = {"req": pending,
                              "remaining": pending["steps"]}


def profile_state() -> Optional[Dict[str, object]]:
    """Beacon payload: the capture in flight or the last terminal result
    (kept until a newer request supersedes it); None = nothing to say."""
    with _profile_lock:
        if _profile["active"] is not None:
            req = _profile["active"]["req"]  # type: ignore[index]
            return {"id": req["id"], "status": "active",
                    "dir": req["dir"], "steps": req["steps"]}
        if _profile["result"] is not None:
            return dict(_profile["result"])  # type: ignore[arg-type]
    return None


def _reset_profile_state() -> None:
    """Tests: forget every request/capture/result."""
    with _profile_lock:
        _profile.update(last_id=0, pending=None, active=None, result=None)


def collect_device_stats() -> Dict[str, float]:
    """Best-effort per-process accelerator + step stats; {} when neither is
    available. Step stats publish WITHOUT a jax runtime — a PyTorch or
    plain-Python loop wrapped in telemetry.step() still feeds the progress
    beacon the coordinator's hang detection watches (device stats alone
    stay jax-gated: this module never imports jax itself)."""
    out: Dict[str, float] = {}
    per_device: list = []
    jax = None
    if "jax" in sys.modules:
        try:
            jax = sys.modules["jax"]
            devices = jax.local_devices()
        except Exception:  # noqa: BLE001 — telemetry must never break the task
            jax, devices = None, []
        if jax is not None:
            out["device_count"] = float(len(devices))
            in_use = peak = 0.0
            for d in devices:
                try:
                    stats = d.memory_stats() or {}
                except Exception:  # noqa: BLE001
                    stats = {}
                b = float(stats.get("bytes_in_use", 0) or 0)
                p = float(stats.get("peak_bytes_in_use", b) or b)
                in_use += b
                peak += p
                per_device.append({"kind": getattr(d, "device_kind", "?"),
                                   "bytes_in_use": b,
                                   "peak_bytes_in_use": p})
            out["hbm_bytes_in_use"] = in_use
            out["hbm_peak_bytes"] = peak
            out["devices"] = per_device  # type: ignore[assignment]
    util = step_stats()
    if util:
        out.update(util)
        kind = per_device[0]["kind"] if per_device else ""
        peak_fl = next((v for k, v in PEAK_BF16_FLOPS.items()
                        if str(kind).startswith(k)), None)
        if jax is not None and peak_fl \
                and util.get("model_flops_per_sec"):
            # flops passed to step() are the model's GLOBAL per-step FLOPs
            # (the 6·N·B·S convention over the global batch), so the
            # denominator must be the GLOBAL device pool — local devices
            # alone would overstate MFU by process_count on multi-host
            # slices.
            try:
                n_global = jax.device_count()
            except Exception:  # noqa: BLE001
                n_global = len(per_device) or 1
            out["mfu_vs_peak_bf16"] = (util["model_flops_per_sec"]
                                       / (peak_fl * n_global))
    phases = phase_stats()
    if phases:
        # Step-time attribution: rides the metrics file → heartbeat
        # beacon → tony_step_phase_seconds gauges + the `top` phase bar.
        out["step_phases"] = phases  # type: ignore[assignment]
    prof = profile_state()
    if prof is not None:
        # On-demand device capture status/result (the coordinator emits
        # TASK_PROFILED and the CLI polls it off profile.status).
        out["profile"] = prof  # type: ignore[assignment]
    quant = sys.modules.get("tony_tpu.ops.quant")
    if quant is not None:
        # One-time quantization-fallback event (tony.train.matmul-dtype
        # refused on this backend → degraded to bf16): surfaced on the
        # beacon so the degrade is visible in metrics/top, not only in a
        # log line. Checked via sys.modules so a job that never touched
        # the quant path never imports it (or jax) from here.
        fb = quant.fallback_events()
        if fb:
            out["quant_fallback"] = fb  # type: ignore[assignment]
    return out


def write_stats_once(path: str) -> bool:
    stats = collect_device_stats()
    if not stats:
        return False
    stats["ts"] = time.time()
    stats["pid"] = os.getpid()
    try:
        from tony_tpu.utils.durable import atomic_write

        atomic_write(path, json.dumps(stats).encode("utf-8"))
        return True
    except OSError:
        return False


def _loop(path: str, interval_s: float) -> None:
    while True:
        # On-demand profiling directive intake first, so a request
        # written just before this tick arms at the very next boundary.
        try:
            _poll_profile_request()
        except Exception:  # noqa: BLE001 — telemetry must never die
            pass
        write_stats_once(path)
        time.sleep(interval_s)


def maybe_start(interval_s: float = 3.0) -> bool:
    """Start the reporter iff TONY_METRICS_FILE is set and it isn't running
    yet. Called from tony_tpu/__init__ — a bare import inside a task is
    enough to light up HBM telemetry. ``TONY_TELEMETRY_INTERVAL_S``
    overrides the cadence (progress-liveness tests tighten it so step
    counters publish faster than the progress deadline)."""
    global _thread
    path = os.environ.get(constants.METRICS_FILE, "")
    if not path:
        return False
    try:
        interval_s = float(
            os.environ.get(constants.TELEMETRY_INTERVAL_ENV, "")
            or interval_s)
    except ValueError:
        pass
    with _started:
        if _thread is not None and _thread.is_alive():
            return True
        _thread = threading.Thread(target=_loop, args=(path, interval_s),
                                   name="tony-telemetry", daemon=True)
        _thread.start()
        return True


def read_stats(path: str) -> Dict[str, float]:
    """Monitor side: read the latest reporter snapshot ({} if absent)."""
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


# ---------------------------------------------------------------------------
# Hung-task diagnostics: pre-registered all-thread stack dump.
#
# When the coordinator declares a task HUNG (progress frozen, heartbeats
# alive — coordinator/liveness.py) the executor signals the USER process
# group with the signal it exported as TONY_STACKDUMP_SIGNAL. This handler
# — registered at `import tony_tpu`, i.e. before the user code can wedge —
# makes that signal dump every thread's stack to stderr (the task log),
# turning "it just stopped" postmortems into tracebacks.
# ---------------------------------------------------------------------------
_dump_registered = False


def install_stack_dump_handler(stream=None) -> bool:
    """Register a faulthandler all-thread stack dump on the signal named by
    ``TONY_STACKDUMP_SIGNAL`` (exported by the executor into the user
    env). No-op without the env var. A handler the user already installed
    on that signal is detected and warned about, never broken: the dump
    chains to it (both run). Returns True iff the dump handler is armed."""
    global _dump_registered
    spec = os.environ.get(constants.STACKDUMP_SIGNAL, "")
    if not spec:
        return False
    if _dump_registered:
        return True
    try:
        signum = int(spec)
    except ValueError:
        return False
    import faulthandler
    import logging
    import signal as _signal

    try:
        existing = _signal.getsignal(signum)
    except (ValueError, OSError):
        return False
    chain = callable(existing) and \
        existing is not _signal.default_int_handler
    if chain:
        # The user process got here with its own handler already on the
        # dump signal (framework or user code). Do not break it — chain —
        # but say so, because a handler that exits would still cut the
        # dump short. Chaining over SIG_DFL would instead re-run the
        # signal's DEFAULT action (terminate, for SIGUSR1/2) and kill the
        # process we are trying to diagnose — hence callable-only.
        logging.getLogger(__name__).warning(
            "signal %d already has a user handler (%r); chaining the "
            "tony-tpu stack-dump handler in front of it — hung-task "
            "dumps will run both", signum, existing)
    try:
        faulthandler.register(signum, file=stream or sys.stderr,
                              all_threads=True, chain=chain)
    except (ValueError, OSError, RuntimeError, AttributeError):
        # Non-main interpreter, closed stderr, or a platform without
        # faulthandler signals: diagnostics are best-effort, never fatal.
        return False
    _dump_registered = True
    return True
