"""Fixture smoke for the default alert packs: evaluate both packs
against a ``metrics.prom`` snapshot in immediate mode (for-durations
ignored) and compare the firing set against ``--expect``.

    python -m tony_tpu.alerts <metrics.prom> [--expect rule-a,rule-b]

Exit 0 iff the firing rule set equals the expected set (empty by
default — the healthy fixture). The no-deps CI lint job runs this over
two checked-in fixtures: healthy → nothing fires, breaching → the
expected set fires. Stdlib only, like the engine itself.
"""

from __future__ import annotations

import argparse
import sys
from typing import List

from tony_tpu.alerts.rules import (
    AlertEngine,
    PromSource,
    default_fleet_pack,
    default_job_pack,
)


def main(argv: List[str] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tony_tpu.alerts",
        description="evaluate the default alert packs against a "
                    "metrics.prom snapshot (immediate mode)")
    ap.add_argument("prom", help="path to a Prometheus text exposition")
    ap.add_argument("--expect", default="",
                    help="comma-separated rule names that must be "
                         "firing (default: none)")
    args = ap.parse_args(argv)

    with open(args.prom, "r", encoding="utf-8") as fh:
        source = PromSource(fh.read())

    engine = AlertEngine(default_job_pack() + default_fleet_pack(),
                         immediate=True)
    engine.evaluate(source)
    firing = sorted(row["rule"] for row in engine.firing())
    expected = sorted(r for r in args.expect.split(",") if r.strip())

    for row in engine.snapshot():
        mark = "FIRING" if row["state"] == "firing" else "ok"
        val = "" if row["value"] is None else f" value={row['value']:.4g}"
        print(f"{mark:>6}  {row['rule']} [{row['severity']}]{val}")

    if firing != expected:
        print(f"firing set mismatch: got {firing}, expected {expected}",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
