"""Declarative alert rules, the pending→firing→resolved state machine,
and SLO error-budget burn-rate accounting.

A :class:`Rule` names a metric family (resolved against
``tony_tpu.metrics.SERIES`` — tonylint's ``alert-registry`` rule holds
that both ways), a comparison, and a for-duration; the
:class:`AlertEngine` evaluates a pack of rules against a *source* each
tick and walks each rule through ``ok → pending → firing → resolved``.
One bad tick never pages: a breach must persist ``for_s`` seconds
(hysteresis) before the transition to ``firing``.

Rule kinds:

==========  =============================================================
gauge       the family's latest sample breaches the threshold
rate        windowed increase/second (``MetricsRegistry.rate``) over a
            counter — or a cumulative gauge, which makes the rate a
            *fraction of wall time* (the live INPUT_BOUND signal)
quantile    windowed quantile (``MetricsRegistry.quantile_over``) over a
            histogram ring breaches a latency bound
absent      the family has no samples at all — dead telemetry
burn        multi-window error-budget burn rate from an :class:`Slo`:
            ``bad_fraction(window) / (1 - objective)`` must exceed the
            factor on BOTH the long and the short window (the classic
            two-window page discipline: sensitive to fast burns, immune
            to old stale breaches)
==========  =============================================================

Every rule evaluates across all label sets of its family that contain
``match`` — a per-task family breaches when ANY task breaches, and the
worst offender's labels ride the transition as evidence.

Sources: :class:`RegistrySource` (a live ``MetricsRegistry`` — the
coordinator monitor tick and the fleet daemon tick) and
:class:`PromSource` (a parsed ``metrics.prom`` exposition — the CI
fixture smoke and offline evaluation; windowed kinds that need history
are honestly *unevaluable* there and never fire, except ``burn``, which
degrades to the instantaneous bad-fraction of the snapshot).

An unevaluable rule (missing family, no samples in window) keeps its
current state: absent data neither pages nor resolves a firing alert.

Stdlib only; no tony_tpu imports beyond the SERIES registry, so the
no-deps CI lint job can run the fixture smoke (`python -m
tony_tpu.alerts`).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

# -- alert states ------------------------------------------------------------
STATE_OK = "ok"
STATE_PENDING = "pending"
STATE_FIRING = "firing"
#: journaled transition closing a pending or firing episode
STATE_RESOLVED = "resolved"

#: every state a REC_ALERT / REC_FLEET_ALERT record may carry
JOURNAL_STATES = (STATE_PENDING, STATE_FIRING, STATE_RESOLVED)

SEV_PAGE = "page"
SEV_WARN = "warn"

_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
}


@dataclasses.dataclass(frozen=True)
class Rule:
    """One declarative alert rule (see the kind table in the module
    docstring). ``threshold`` is the breach bound; ``for_s`` the
    hysteresis; ``match`` a label filter ANDed over the family's label
    sets."""

    name: str
    kind: str                   # gauge | rate | quantile | absent | burn
    series: str
    op: str = ">"
    threshold: float = 0.0
    for_s: float = 0.0
    window_s: float = 60.0
    q: float = 0.99             # quantile kind only
    match: Tuple[Tuple[str, str], ...] = ()
    severity: str = SEV_WARN
    summary: str = ""
    # burn kind only (compiled from an Slo):
    objective: float = 0.0
    long_s: float = 0.0
    short_s: float = 0.0
    factor: float = 0.0

    def __post_init__(self) -> None:
        if self.kind not in ("gauge", "rate", "quantile", "absent",
                             "burn"):
            raise ValueError(f"unknown rule kind {self.kind!r}")
        if self.op not in _OPS:
            raise ValueError(f"unknown rule op {self.op!r}")
        if self.severity not in (SEV_PAGE, SEV_WARN):
            raise ValueError(f"unknown severity {self.severity!r}")


@dataclasses.dataclass(frozen=True)
class Slo:
    """A service-level objective over a continuous signal: a sample is
    *bad* when ``op(sample, threshold)`` holds, the error budget is
    ``1 - objective``, and the derived rule pages when the budget burns
    at ``factor``x on both windows. ``compile()`` lowers it to a
    ``burn`` :class:`Rule` so the one state machine drives both plain
    rules and SLOs."""

    name: str
    series: str
    op: str
    threshold: float
    objective: float = 0.9
    long_s: float = 300.0
    short_s: float = 60.0
    factor: float = 2.0
    for_s: float = 0.0
    match: Tuple[Tuple[str, str], ...] = ()
    severity: str = SEV_PAGE
    summary: str = ""

    def compile(self) -> Rule:
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"SLO objective must be in (0, 1), got {self.objective}")
        return Rule(
            name=self.name, kind="burn", series=self.series, op=self.op,
            threshold=self.threshold, for_s=self.for_s, match=self.match,
            severity=self.severity,
            summary=self.summary or f"SLO {self.name} burn-rate breach",
            objective=self.objective, long_s=self.long_s,
            short_s=self.short_s, factor=self.factor)


@dataclasses.dataclass(frozen=True)
class Transition:
    """One state-machine step the caller journals/announces. ``journal``
    is the dedup fence: False when the write-ahead journal already holds
    this (rule, state) — a recovered engine re-entering its replayed
    state must not duplicate the record."""

    rule: str
    state: str                  # pending | firing | resolved
    severity: str
    value: Optional[float]
    labels: Dict[str, str]
    summary: str
    journal: bool = True


# ---------------------------------------------------------------------------
# evaluation sources
# ---------------------------------------------------------------------------
class RegistrySource:
    """Evaluate against a live :class:`tony_tpu.metrics.MetricsRegistry`
    — full windowed semantics (rate / quantile_over / gauge rings)."""

    def __init__(self, registry: Any, now: Optional[float] = None):
        self._reg = registry
        self.now = now if now is not None else time.monotonic()

    def label_sets(self, series: str) -> List[Dict[str, str]]:
        return list(self._reg.label_sets(series))

    def sample(self, series: str,
               labels: Dict[str, str]) -> Optional[float]:
        return self._reg.sample(series, labels)

    def rate(self, series: str, labels: Dict[str, str],
             window_s: float) -> Optional[float]:
        return self._reg.rate(series, labels, window_s, now=self.now)

    def quantile(self, series: str, labels: Dict[str, str],
                 window_s: float, q: float) -> Optional[float]:
        return self._reg.quantile_over(series, labels, window_s, q,
                                       now=self.now)

    def points(self, series: str,
               labels: Dict[str, str]) -> List[Tuple[float, float]]:
        return self._reg.gauge_points(series, labels)


class PromSource:
    """Evaluate against a parsed Prometheus text exposition (a
    ``metrics.prom`` snapshot). No history: ``rate`` is unevaluable
    (None), ``quantile`` uses the full-lifetime cumulative histogram,
    and ``burn`` sees each series as one instantaneous sample."""

    def __init__(self, text: str, now: Optional[float] = None):
        self.now = now if now is not None else 0.0
        # family → [(labels, value)]
        self._values: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
        # family → [(labels, {"buckets": [...], "counts": [...], count})]
        self._hists: Dict[str, List[Tuple[Dict[str, str],
                                          Dict[str, Any]]]] = {}
        self._parse(text)

    @staticmethod
    def _parse_labels(raw: str) -> Dict[str, str]:
        out: Dict[str, str] = {}
        depth = raw.strip()
        if not depth:
            return out
        for part in _split_label_pairs(depth):
            if "=" not in part:
                continue
            k, _, v = part.partition("=")
            v = v.strip()
            if v.startswith('"') and v.endswith('"'):
                v = v[1:-1]
            out[k.strip()] = (v.replace('\\"', '"')
                              .replace("\\n", "\n").replace("\\\\", "\\"))
        return out

    def _parse(self, text: str) -> None:
        # (family, labels_sans_le) → {le_bound: cum_count}
        buckets: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                      Dict[float, float]] = {}
        for line in text.splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            name_part, _, value_part = line.rpartition(" ")
            name_part = name_part.strip()
            try:
                value = float(value_part)
            except ValueError:
                continue
            if "{" in name_part:
                name, _, rest = name_part.partition("{")
                labels = self._parse_labels(rest.rstrip("}"))
            else:
                name, labels = name_part, {}
            if name.endswith("_bucket") and "le" in labels:
                fam = name[:-len("_bucket")]
                le = labels.pop("le")
                bound = float("inf") if le in ("+Inf", "inf") \
                    else float(le)
                key = (fam, tuple(sorted(labels.items())))
                buckets.setdefault(key, {})[bound] = value
                continue
            if name.endswith("_sum") or name.endswith("_count"):
                continue
            self._values.setdefault(name, []).append((labels, value))
        for (fam, lkey), by_bound in buckets.items():
            bounds = sorted(b for b in by_bound if b != float("inf"))
            cum = [by_bound[b] for b in bounds]
            # de-cumulate into per-bucket counts + overflow
            counts, prev = [], 0.0
            for c in cum:
                counts.append(max(0.0, c - prev))
                prev = c
            total = by_bound.get(float("inf"), prev)
            counts.append(max(0.0, total - prev))
            self._hists.setdefault(fam, []).append(
                (dict(lkey), {"buckets": bounds, "counts": counts,
                              "count": total}))

    def label_sets(self, series: str) -> List[Dict[str, str]]:
        out = [labels for labels, _ in self._values.get(series, [])]
        out += [labels for labels, _ in self._hists.get(series, [])]
        return out

    def sample(self, series: str,
               labels: Dict[str, str]) -> Optional[float]:
        for cand, value in self._values.get(series, []):
            if cand == labels:
                return value
        return None

    def rate(self, series: str, labels: Dict[str, str],
             window_s: float) -> Optional[float]:
        return None             # no history in a snapshot — unevaluable

    def quantile(self, series: str, labels: Dict[str, str],
                 window_s: float, q: float) -> Optional[float]:
        for cand, snap in self._hists.get(series, []):
            if cand == labels:
                if not snap["count"]:
                    return None
                return bucket_quantile(snap["buckets"], snap["counts"], q)
        return None

    def points(self, series: str,
               labels: Dict[str, str]) -> List[Tuple[float, float]]:
        v = self.sample(series, labels)
        return [(self.now, v)] if v is not None else []


def _split_label_pairs(raw: str) -> List[str]:
    """Split ``a="x",b="y,z"`` on commas outside quotes."""
    out, cur, in_q, esc = [], [], False, False
    for ch in raw:
        if esc:
            cur.append(ch)
            esc = False
            continue
        if ch == "\\":
            cur.append(ch)
            esc = True
            continue
        if ch == '"':
            in_q = not in_q
        if ch == "," and not in_q:
            out.append("".join(cur))
            cur = []
            continue
        cur.append(ch)
    if cur:
        out.append("".join(cur))
    return out


def bucket_quantile(bounds: Sequence[float], counts: Sequence[float],
                    q: float) -> float:
    """Quantile from per-bucket counts (+overflow last) by linear
    interpolation inside the owning bucket — the same semantics as
    ``coordphases.histogram_quantile``, over a de-cumulated shape."""
    total = sum(counts)
    if total <= 0 or not bounds:
        return 0.0
    rank = q * total
    cum, lo = 0.0, 0.0
    for bound, c in zip(bounds, counts):
        if cum + c >= rank and c > 0:
            return lo + (bound - lo) * (rank - cum) / c
        cum += c
        lo = bound
    return float(bounds[-1])


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------
class _RuleState:
    __slots__ = ("state", "since", "value", "labels", "logged")

    def __init__(self) -> None:
        self.state = STATE_OK
        self.since = 0.0
        self.value: Optional[float] = None
        self.labels: Dict[str, str] = {}
        self.logged: Optional[str] = None   # last journaled state


class AlertEngine:
    """Holds a pack's per-rule state machines. Thread-safe: the
    evaluating tick and the RPC/status snapshot readers share a lock.
    ``immediate=True`` ignores for-durations (the CI fixture smoke: one
    snapshot, one verdict)."""

    def __init__(self, rules: Sequence[Rule],
                 clock: Callable[[], float] = time.monotonic,
                 immediate: bool = False):
        by_name: Dict[str, Rule] = {}
        for r in rules:
            if r.name in by_name:
                raise ValueError(f"duplicate rule name {r.name!r}")
            by_name[r.name] = r
        self._rules = by_name
        self._clock = clock
        self._immediate = immediate
        self._lock = threading.Lock()
        self._state: Dict[str, _RuleState] = {
            name: _RuleState() for name in by_name}

    @property
    def rules(self) -> List[Rule]:
        return list(self._rules.values())

    # -- recover seeding -------------------------------------------------
    def seed(self, replayed: Dict[str, str]) -> None:
        """Install the journal-replayed last state per rule (the recover
        path). ``firing`` re-arms as firing, ``pending`` restarts its
        hysteresis clock, ``resolved`` is ok — and the dedup fence
        remembers what the journal already holds, so the first
        post-recover transition into the same state is not re-journaled."""
        now = self._clock()
        with self._lock:
            for name, state in replayed.items():
                st = self._state.get(name)
                if st is None:
                    continue        # rule retired since that journal life
                st.logged = state if state in JOURNAL_STATES else None
                if state == STATE_FIRING:
                    st.state = STATE_FIRING
                    st.since = now
                elif state == STATE_PENDING:
                    st.state = STATE_PENDING
                    st.since = now
                else:
                    st.state = STATE_OK

    # -- evaluation ------------------------------------------------------
    def evaluate(self, source: Any,
                 now: Optional[float] = None) -> List[Transition]:
        """One tick: evaluate every rule against ``source`` and return
        the state transitions that happened (empty in steady state)."""
        now = now if now is not None else self._clock()
        out: List[Transition] = []
        for rule in self._rules.values():
            breached, value, labels = _evaluate_rule(rule, source)
            with self._lock:
                st = self._state[rule.name]
                if value is not None:
                    st.value, st.labels = value, labels
                if breached is None:
                    continue        # unevaluable: hold the current state
                if breached:
                    if st.state == STATE_OK:
                        if rule.for_s > 0 and not self._immediate:
                            st.state, st.since = STATE_PENDING, now
                            out.append(self._transition_locked(
                                rule, st, STATE_PENDING, value, labels))
                            continue
                        st.state, st.since = STATE_FIRING, now
                        out.append(self._transition_locked(
                            rule, st, STATE_FIRING, value, labels))
                    elif st.state == STATE_PENDING and (
                            self._immediate
                            or now - st.since >= rule.for_s):
                        st.state, st.since = STATE_FIRING, now
                        out.append(self._transition_locked(
                            rule, st, STATE_FIRING, value, labels))
                elif st.state in (STATE_PENDING, STATE_FIRING):
                    st.state, st.since = STATE_OK, now
                    out.append(self._transition_locked(
                        rule, st, STATE_RESOLVED, value, labels))
        return out

    def _transition_locked(self, rule: Rule, st: _RuleState, state: str,
                           value: Optional[float],
                           labels: Dict[str, str]) -> Transition:
        journal = st.logged != state
        st.logged = state
        return Transition(rule=rule.name, state=state,
                          severity=rule.severity, value=value,
                          labels=dict(labels),
                          summary=rule.summary or rule.name,
                          journal=journal)

    def resolve_all(self) -> List[Transition]:
        """Force every pending/firing rule back to ok (clean teardown of
        a SUCCEEDED job: the journal must not end with an alert
        firing)."""
        now = self._clock()
        out: List[Transition] = []
        with self._lock:
            for rule in self._rules.values():
                st = self._state[rule.name]
                if st.state in (STATE_PENDING, STATE_FIRING):
                    st.state, st.since = STATE_OK, now
                    out.append(self._transition_locked(
                        rule, st, STATE_RESOLVED, st.value, st.labels))
        return out

    # -- snapshots -------------------------------------------------------
    def snapshot(self) -> List[Dict[str, Any]]:
        now = self._clock()
        rows = []
        with self._lock:
            for rule in self._rules.values():
                st = self._state[rule.name]
                rows.append({
                    "rule": rule.name, "state": st.state,
                    "severity": rule.severity, "kind": rule.kind,
                    "series": rule.series,
                    "value": st.value, "labels": dict(st.labels),
                    "since_s": round(now - st.since, 3)
                    if st.state != STATE_OK else None,
                    "summary": rule.summary or rule.name})
        return rows

    def firing(self) -> List[Dict[str, Any]]:
        return [r for r in self.snapshot()
                if r["state"] == STATE_FIRING]

    def firing_count(self) -> Dict[str, int]:
        """firing tally by severity — the ``tony_alerts_firing`` gauge
        refresh (every registered severity present, so a resolve zeroes
        the gauge instead of leaving it frozen)."""
        out = {SEV_PAGE: 0, SEV_WARN: 0}
        for row in self.firing():
            out[row["severity"]] = out.get(row["severity"], 0) + 1
        return out


def _match(labels: Dict[str, str],
           match: Tuple[Tuple[str, str], ...]) -> bool:
    return all(labels.get(k) == v for k, v in match)


def _evaluate_rule(rule: Rule, source: Any
                   ) -> Tuple[Optional[bool], Optional[float],
                              Dict[str, str]]:
    """(breached, worst value, worst labels); breached None =
    unevaluable (no data — hold state)."""
    sets = [ls for ls in source.label_sets(rule.series)
            if _match(ls, rule.match)]
    if rule.kind == "absent":
        if not sets:
            return True, None, {}
        present = any(source.sample(rule.series, ls) is not None
                      or source.quantile(rule.series, ls, rule.window_s,
                                         rule.q) is not None
                      for ls in sets)
        return (not present), None, {}
    samples: List[Tuple[float, Dict[str, str]]] = []
    for ls in sets:
        if rule.kind == "gauge":
            v: Optional[float] = source.sample(rule.series, ls)
        elif rule.kind == "rate":
            v = source.rate(rule.series, ls, rule.window_s)
        elif rule.kind == "quantile":
            v = source.quantile(rule.series, ls, rule.window_s, rule.q)
        else:                       # burn
            v = _burn_rate(rule, source, ls)
        if v is not None:
            samples.append((v, ls))
    if not samples:
        return None, None, {}
    op = _OPS[rule.op]
    if rule.kind == "burn":
        # burn value is "budget-burn multiple": always bigger-is-worse
        worst, labels = max(samples, key=lambda s: s[0])
        return worst >= rule.factor, worst, labels
    breaching = [(v, ls) for v, ls in samples if op(v, rule.threshold)]
    if breaching:
        # worst offender: the sample deepest past the threshold
        worst, labels = max(
            breaching,
            key=lambda s: s[0] if rule.op in (">", ">=") else -s[0])
        return True, worst, labels
    worst, labels = max(
        samples, key=lambda s: s[0] if rule.op in (">", ">=") else -s[0])
    return False, worst, labels


def _burn_rate(rule: Rule, source: Any,
               labels: Dict[str, str]) -> Optional[float]:
    """min(burn(long), burn(short)) — the two-window AND collapsed into
    one number: >= factor exactly when BOTH windows breach."""
    points = source.points(rule.series, labels)
    if not points:
        return None
    now = getattr(source, "now", points[-1][0])
    budget = 1.0 - rule.objective
    op = _OPS[rule.op]
    burns = []
    for window in (rule.long_s, rule.short_s):
        cutoff = now - window
        in_window = [v for ts, v in points if ts >= cutoff]
        if not in_window:
            # stale series: the newest sample anchors the short window
            in_window = [points[-1][1]]
        bad = sum(1 for v in in_window if op(v, rule.threshold))
        burns.append((bad / len(in_window)) / budget)
    return min(burns)


# ---------------------------------------------------------------------------
# default packs
# ---------------------------------------------------------------------------
def _f(conf: Any, key: str, default: float) -> float:
    if conf is None:
        return default
    try:
        v = conf.get(key, default)
        return float(v) if v not in (None, "") else default
    except (TypeError, ValueError):
        return default


def default_job_pack(conf: Any = None) -> List[Rule]:
    """Job-scope defaults, evaluated on the coordinator monitor tick.
    Thresholds come from ``tony.alerts.*`` conf keys so a drill (or a
    latency-sensitive serving job) can tighten them without code."""
    from tony_tpu.conf import keys as K

    for_s = _f(conf, K.ALERTS_FOR_S, 10.0)
    return [
        Rule(name="heartbeat-age", kind="gauge",
             series="tony_task_heartbeat_age_seconds", op=">",
             threshold=_f(conf, K.ALERTS_HEARTBEAT_AGE_S, 30.0),
             for_s=for_s, severity=SEV_PAGE,
             summary="a task's heartbeat age breached the liveness "
                     "budget — the gang is about to lose a member"),
        Rule(name="input-bound", kind="rate",
             series="tony_step_phase_seconds",
             match=(("phase", "data_wait"),), op=">",
             threshold=_f(conf, K.ALERTS_DATA_WAIT_FRACTION, 0.5),
             window_s=60.0, for_s=for_s * 3, severity=SEV_WARN,
             summary="the gang spends most of its wall time waiting on "
                     "input — live INPUT_BOUND (rate of the cumulative "
                     "data_wait phase = fraction of wall)"),
        Rule(name="journal-fsync-p99", kind="quantile",
             series="tony_journal_fsync_seconds", q=0.99,
             window_s=300.0, op=">",
             threshold=_f(conf, K.ALERTS_FSYNC_P99_S, 0.05),
             for_s=for_s * 3, severity=SEV_WARN,
             summary="write-ahead journal fsync p99 breached the "
                     "JOURNAL_BOUND budget (BENCH_SCALE_r01 measured "
                     "63ms at 512 wide — ROADMAP item 3 by numbers)"),
        Slo(name="step-time-slo",
            series="tony_task_steps_per_sec", op="<",
            threshold=_f(conf, K.ALERTS_MIN_STEPS_PER_SEC, 0.0),
            objective=_f(conf, K.ALERTS_SLO_OBJECTIVE, 0.9),
            long_s=_f(conf, K.ALERTS_WINDOW_LONG_S, 300.0),
            short_s=_f(conf, K.ALERTS_WINDOW_SHORT_S, 60.0),
            factor=_f(conf, K.ALERTS_BURN_FACTOR, 2.0),
            for_s=for_s, severity=SEV_PAGE,
            summary="step-time SLO budget burning: tasks below the "
                    "step-rate floor on both burn windows").compile(),
    ]


def default_fleet_pack(conf: Any = None) -> List[Rule]:
    """Fleet-scope defaults, evaluated on the fleet daemon tick. The
    fleet for-duration is long (60s) on purpose: a fleet alert is a
    capacity/goodput story, not a single-tick blip."""
    from tony_tpu.conf import keys as K

    for_s = _f(conf, K.ALERTS_FLEET_FOR_S, 60.0)
    return [
        Slo(name="goodput-slo",
            series="tony_fleet_goodput_fraction", op="<",
            threshold=_f(conf, K.ALERTS_GOODPUT_FLOOR, 0.5),
            objective=_f(conf, K.ALERTS_SLO_OBJECTIVE, 0.9),
            long_s=_f(conf, K.ALERTS_WINDOW_LONG_S, 300.0) * 6,
            short_s=_f(conf, K.ALERTS_WINDOW_SHORT_S, 60.0) * 5,
            factor=_f(conf, K.ALERTS_BURN_FACTOR, 2.0),
            for_s=for_s, severity=SEV_PAGE,
            summary="fleet goodput fraction below the floor on both "
                    "burn windows — chip-seconds are burning on "
                    "overhead, not train steps").compile(),
        Rule(name="quarantine-spike", kind="rate",
             series="tony_fleet_quarantines_total", op=">",
             threshold=_f(conf, K.ALERTS_QUARANTINE_PER_MIN, 3.0) / 60.0,
             window_s=300.0, for_s=for_s, severity=SEV_WARN,
             summary="host quarantines applied faster than the "
                     "attribution budget — correlated hardware event "
                     "or a flapping health scorer"),
        Rule(name="queue-wait-p99", kind="quantile",
             series="tony_fleet_queue_wait_seconds", q=0.99,
             window_s=1800.0, op=">",
             threshold=_f(conf, K.ALERTS_QUEUE_WAIT_P99_S, 600.0),
             for_s=for_s, severity=SEV_WARN,
             summary="submit-to-grant p99 wait breached the queue "
                     "budget — the pool is starved or fragmented"),
    ]


def pack_series(pack: Sequence[Rule]) -> List[str]:
    """Every metric family a pack references (the ``alert-registry``
    lint resolves each against metrics.SERIES)."""
    return sorted({r.series for r in pack})
