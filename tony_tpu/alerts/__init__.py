"""Watchtower: the live SLO/alerting engine (ISSUE 19).

Everything observability built so far is post-mortem or operator-pulled;
this package watches the metric families continuously and says "this is
breaching NOW". Stdlib only — the rule engine, the pending→firing→
resolved state machine and the burn-rate error-budget accounting all
live in :mod:`tony_tpu.alerts.rules`; the coordinator monitor tick and
the fleet daemon tick evaluate their packs behind the never-blocks-the-
tick degrade contract and journal every transition write-ahead
(``REC_ALERT`` / ``REC_FLEET_ALERT``), so a firing alert survives a
SIGKILL + ``--recover``.
"""

from tony_tpu.alerts.rules import (  # noqa: F401
    AlertEngine,
    PromSource,
    RegistrySource,
    Rule,
    Slo,
    Transition,
    default_fleet_pack,
    default_job_pack,
    pack_series,
)
