"""History web portal: the reference's Play-framework history server,
re-imagined as a dependency-free stdlib HTTP server.

Reference model: ``tony-portal`` — routes (``conf/routes:1-5``):
jobs index ``/``, per-job config ``/config/:jobId``, events
``/jobs/:jobId``, logs ``/logs/:jobId``; Guava caches warming parsed
metadata/config/events/logs (``cache/CacheWrapper.java:82-126``); background
``HistoryFileMover`` (intermediate → finished/yyyy/MM/dd, every 5 min) and
``HistoryFilePurger`` (retention deletes) singletons (``Module.java:14-22``).

Every view is served as HTML (human) or JSON (``?format=json`` — the
machine-readable surface the reference lacks). Log links only resolve paths
recorded in the job's own TASK_FINISHED events, never caller-supplied ones.
"""

from __future__ import annotations

import html
import json
import logging
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from tony_tpu import constants
from tony_tpu.events import history

log = logging.getLogger(__name__)

_CACHE_TTL_S = 30.0


class _Cache:
    """TTL cache per (kind, job) — the CacheWrapper analogue. Finished jobs
    never change, so entries for terminal jobs are kept until evicted."""

    def __init__(self, ttl_s: float = _CACHE_TTL_S, max_entries: int = 256):
        self._data: Dict[Tuple[str, str], Tuple[float, Any]] = {}
        self._ttl = ttl_s
        self._max = max_entries
        self._lock = threading.Lock()

    def get(self, kind: str, key: str):
        with self._lock:
            hit = self._data.get((kind, key))
        if hit and (time.monotonic() - hit[0]) < self._ttl:
            return hit[1]
        return None

    def put(self, kind: str, key: str, value) -> None:
        with self._lock:
            if len(self._data) >= self._max:
                oldest = min(self._data, key=lambda k: self._data[k][0])
                del self._data[oldest]
            self._data[(kind, key)] = (time.monotonic(), value)


class PortalServer:
    """Serves the four history views + JSON API; owns mover/purger threads."""

    def __init__(self, history_root: str, port: int = 0,
                 host: str = "127.0.0.1", mover_interval_s: float = 300.0,
                 purger_interval_s: float = 3600.0,
                 retention_days: int = 30, token: str = "",
                 tls_cert: str = "", tls_key: str = "",
                 fleet_dir: str = ""):
        # Optional bearer auth: with a token set, every request must carry
        # "Authorization: Bearer <token>" or gets 401. The reference portal
        # ran behind keytab-login Play infra (hadoop/Requirements.java:
        # 24-70); a shared token is the TPU-native minimum for a portal
        # that binds beyond localhost. TONY_PORTAL_TOKEN in `tony-tpu
        # portal` / module main.
        self.token = token
        self.history_root = history_root
        # Fleet scheduler view (/fleet): explicit dir, else discovered —
        # a fleet daemon's history root lives INSIDE its fleet dir, so
        # the parent holding a fleet journal is the fleet.
        if not fleet_dir:
            parent = os.path.dirname(os.path.abspath(history_root))
            if os.path.exists(os.path.join(
                    parent, constants.FLEET_JOURNAL_FILE)):
                fleet_dir = parent
        self.fleet_dir = fleet_dir
        self.cache = _Cache()
        self._mover = history.HistoryFileMover(history_root)
        self._purger = history.HistoryFilePurger(history_root, retention_days)
        self._mover_interval = mover_interval_s
        self._purger_interval = purger_interval_s
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

        portal = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet; use logging
                log.debug("portal: " + fmt, *args)

            def do_GET(self):  # noqa: N802
                portal._route(self)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        if tls_cert:
            # HTTPS opt-in (same cert pair as the RPC plane): without it a
            # bearer token rides plaintext HTTP, which is only acceptable
            # on localhost. do_handshake_on_connect=False defers the
            # handshake from accept() (which runs in the single
            # serve_forever thread — a stalled client there would hang the
            # whole portal) to the first read, inside the per-request
            # handler thread; Handler.timeout bounds that thread too.
            from tony_tpu.rpc.wire import server_tls_context
            Handler.timeout = 60
            self.httpd.socket = server_tls_context(
                tls_cert, tls_key).wrap_socket(
                    self.httpd.socket, server_side=True,
                    do_handshake_on_connect=False)
        self.scheme = "https" if tls_cert else "http"
        self.port = self.httpd.server_address[1]

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        t = threading.Thread(target=self.httpd.serve_forever,
                             name="tony-portal", daemon=True)
        t.start()
        self._threads.append(t)
        for name, fn, interval in (
                ("tony-history-mover", self._mover.move_once,
                 self._mover_interval),
                ("tony-history-purger", self._purger.purge_once,
                 self._purger_interval)):
            th = threading.Thread(target=self._periodic, name=name,
                                  args=(fn, interval), daemon=True)
            th.start()
            self._threads.append(th)

    def _periodic(self, fn, interval: float) -> None:
        while not self._stop.wait(interval):
            try:
                fn()
            except Exception as e:  # noqa: BLE001
                log.warning("%s failed: %s", fn.__name__, e)

    def stop(self) -> None:
        self._stop.set()
        self.httpd.shutdown()
        self.httpd.server_close()

    @property
    def url(self) -> str:
        return f"{self.scheme}://{self.httpd.server_address[0]}:{self.port}"

    # -- routing ---------------------------------------------------------
    def _route(self, req: BaseHTTPRequestHandler) -> None:
        if self.token:
            import hmac as hmaclib

            # Compare as bytes: compare_digest on str raises TypeError for
            # non-ASCII (headers arrive latin-1-decoded), which would kill
            # the request instead of 401ing; constant-time so the token
            # can't be recovered from 401 latencies.
            auth = req.headers.get("Authorization", "").encode(
                "latin-1", "replace")
            want = f"Bearer {self.token}".encode("latin-1", "replace")
            if not hmaclib.compare_digest(auth, want):
                return self._send(req, 401, "text/plain",
                                  b"unauthorized (bearer token required)")
        parsed = urlparse(req.path)
        parts = [p for p in parsed.path.split("/") if p]
        query = parse_qs(parsed.query)
        as_json = query.get("format", [""])[0] == "json"
        try:
            if not parts:
                return self._jobs_index(req, as_json)
            if parts == ["metrics"]:
                # Bare /metrics: Prometheus text exposition across every
                # LIVE job — the scrape endpoint (per-job HTML stays at
                # /metrics/<job>).
                return self._prom_view(req)
            if parts == ["fleet"]:
                # Fleet scheduler row (tony_tpu/fleet/): live from a
                # running daemon's RPC, exported artifacts otherwise —
                # never the TTL cache, the fleet is always live.
                return self._fleet_view(req, as_json)
            if parts == ["alerts"]:
                # SLO/alert rollup (tony_tpu/alerts/): fleet-scope rule
                # state + every job's journaled alert fold.
                return self._alerts_view(req, as_json)
            if parts == ["whatif"]:
                # Fleet time machine (fleet/simulator.py): replay the
                # recorded journal under counterfactual quotas/pool/
                # priorities passed as query params.
                return self._whatif_view(req, query, as_json)
            view, *rest = parts
            if view in ("config", "jobs", "logs", "logfile",
                        "profiles", "profile", "metrics", "trace",
                        "diagnose") and rest:
                job_id = rest[0]
                if view == "config":
                    return self._config_view(req, job_id, as_json)
                if view == "jobs":
                    return self._events_view(req, job_id, as_json)
                if view == "logs":
                    return self._logs_view(req, job_id, as_json)
                if view == "logfile" and len(rest) >= 2:
                    return self._logfile_view(req, job_id, int(rest[1]),
                                              query)
                if view in ("profiles", "profile"):
                    # /profile/<app> (singular) is the documented spelling
                    # for on-demand captures; both list the same dir.
                    return self._profiles_view(req, job_id, as_json)
                if view == "metrics":
                    return self._metrics_view(req, job_id, as_json)
                if view == "trace":
                    return self._trace_view(req, job_id, as_json)
                if view == "diagnose":
                    return self._diagnose_view(req, job_id, as_json)
            self._send(req, 404, "text/plain", b"not found")
        except Exception as e:  # noqa: BLE001
            log.exception("portal error for %s", req.path)
            self._send(req, 500, "text/plain",
                       f"internal error: {e}".encode())

    # -- views -----------------------------------------------------------
    def _jobs_index(self, req, as_json: bool) -> None:
        rows = history.list_jobs(self.history_root)
        if as_json:
            payload = [dict(app_id=r.app_id, status=r.status, user=r.user,
                            started_ms=r.started_ms) for r in rows]
            return self._send_json(req, payload)
        body = ["<h1>tony-tpu jobs</h1>"]
        if self.fleet_dir:
            body.append("<p><a href='/fleet'>fleet scheduler</a> — "
                        "queue, tenants, grants · "
                        "<a href='/whatif'>whatif</a> — counterfactual "
                        "replay</p>")
        body.append("<p><a href='/alerts'>alerts</a> — SLO rule "
                    "state, fleet + per job</p>")
        body += ["<table border=1 cellpadding=4>",
                 "<tr><th>job</th><th>status</th><th>user</th>"
                 "<th>started</th><th></th></tr>"]
        for r in rows:
            a = html.escape(r.app_id)
            body.append(
                f"<tr><td>{a}</td><td>{html.escape(r.status)}</td>"
                f"<td>{html.escape(r.user)}</td><td>{r.started_iso}</td>"
                f"<td><a href='/jobs/{a}'>events</a> "
                f"<a href='/config/{a}'>config</a> "
                f"<a href='/logs/{a}'>logs</a> "
                f"<a href='/profiles/{a}'>profiles</a> "
                f"<a href='/metrics/{a}'>metrics</a> "
                f"<a href='/trace/{a}'>trace</a> "
                f"<a href='/diagnose/{a}'>diagnose</a></td></tr>")
        body.append("</table>")
        self._send_html(req, "".join(body))

    def _job_dir(self, job_id: str) -> Optional[str]:
        return history.list_job_dirs(self.history_root).get(job_id)

    def _fleet_client(self):
        """FleetClient for a RUNNING daemon (addr file present), else
        None. The live-object bypass for the fleet views: the exported
        fleet.status.json/fleet.prom only refresh on the daemon's
        export cadence — the same staleness the per-job views fixed by
        skipping the TTL cache for in-progress jobs — so a live daemon
        is asked directly and the files stay the dead-daemon fallback."""
        if not self.fleet_dir or not os.path.exists(
                os.path.join(self.fleet_dir, constants.FLEET_ADDR_FILE)):
            return None
        from tony_tpu.fleet.client import FleetClient
        return FleetClient(self.fleet_dir)

    def _fleet_snapshot(self) -> Tuple[Optional[dict], Optional[str]]:
        """(status snapshot, prom text): live from the daemon's RPC
        when it is up, else the exported artifacts."""
        client = self._fleet_client()
        if client is not None:
            try:
                return client.status(), client.prom()
            except Exception as e:  # noqa: BLE001 — stale addr, dying daemon
                log.debug("fleet live bypass failed (%s); serving the "
                          "exported artifacts", e)
            finally:
                client.close()
        snap = prom = None
        try:
            with open(os.path.join(self.fleet_dir,
                                   constants.FLEET_STATUS_FILE),
                      encoding="utf-8") as f:
                snap = json.load(f)
        except (OSError, ValueError):
            pass
        try:
            with open(os.path.join(self.fleet_dir,
                                   constants.FLEET_PROM_FILE),
                      encoding="utf-8") as f:
                prom = f.read()
        except OSError:
            pass
        return snap, prom

    def _fleet_view(self, req, as_json: bool) -> None:
        """Scheduler snapshot + tony_fleet_* families: live from a
        running daemon's RPC (see _fleet_client), falling back to the
        atomically replaced artifacts when the daemon is down."""
        if not self.fleet_dir:
            return self._send(req, 404, "text/plain",
                              b"no fleet dir configured or discovered")
        snap, prom = self._fleet_snapshot()
        if snap is None:
            return self._send(req, 404, "text/plain",
                              b"no fleet status snapshot yet")
        if as_json:
            return self._send_json(req, snap)
        pool = snap.get("pool") or {}
        qw = snap.get("queue_wait") or {}
        body = [f"<h1>fleet — {html.escape(str(snap.get('fleet_dir')))}"
                f"</h1>",
                f"<p>generation {snap.get('generation', '?')} — hosts "
                f"{pool.get('used', '?')}/{pool.get('total', '?')} used "
                f"({pool.get('free', '?')} free), queue depth "
                f"{snap.get('queue_depth', '?')}, wait p50 "
                f"{qw.get('p50_s', 0)}s / p99 {qw.get('p99_s', 0)}s — "
                f"<a href='/whatif'>whatif</a> (counterfactual "
                f"replay)</p>"]
        # Fleet incident verdict (fleet/diagnose.py): the daemon
        # refreshes fleet.incident.json every export; torn/absent
        # degrades to no banner (same posture as incident.json).
        incident = None
        try:
            with open(os.path.join(self.fleet_dir,
                                   constants.FLEET_INCIDENT_FILE),
                      encoding="utf-8") as f:
                incident = json.load(f)
        except (OSError, ValueError):
            pass
        if isinstance(incident, dict) and incident.get("verdict"):
            v = incident["verdict"]
            body.append(
                f"<p><b>verdict: "
                f"{html.escape(str(v.get('category', '?')))}</b> — "
                f"{html.escape(str(v.get('summary', '')))}<br>"
                f"advice: {html.escape(str(v.get('advice', '')))}</p>")
        # Firing-alert banner (tony_tpu/alerts/): quiet when nothing
        # fires; /alerts has the full per-rule table.
        fal = snap.get("alerts") or {}
        if fal.get("degraded") or fal.get("firing"):
            parts = []
            if fal.get("degraded"):
                parts.append("evaluation DEGRADED")
            for r in fal.get("firing") or []:
                parts.append(
                    f"{html.escape(str(r.get('rule', '?')))} "
                    f"[{html.escape(str(r.get('severity', '?')))}]")
            body.append("<p><b>alerts</b> — " + "; ".join(parts)
                        + " (<a href='/alerts'>details</a>)</p>")
        # Host-health cordon banner (fleet/health.py): quiet when the
        # fleet is clean — operators should only see it on an incident.
        health = snap.get("health") or {}
        if health.get("cordoned") or health.get("sick_slices"):
            parts = []
            if health.get("cordoned"):
                parts.append("cordoned hosts: " + html.escape(
                    ", ".join(str(h) for h in health["cordoned"])))
            if health.get("sick_slices"):
                parts.append("sick slices: " + html.escape(
                    ", ".join(str(i) for i in health["sick_slices"])))
            body.append("<p><b>host health</b> — " + "; ".join(parts)
                        + " (see `tony-tpu fleet health`)</p>")
        # Per-tenant goodput ledger table (fleet/ledger.py rollup).
        ledger = snap.get("ledger") or {}
        tenants = snap.get("tenants") or {}
        tenant_led = ledger.get("tenants") or {}
        if tenants or tenant_led:
            body.append("<h2>tenants</h2>"
                        "<table border=1 cellpadding=4><tr>"
                        "<th>tenant</th><th>hosts used/quota</th>"
                        "<th>goodput</th><th>train chip-s</th>"
                        "<th>held chip-s</th><th>queued chip-s lost"
                        "</th><th>warm starts</th></tr>")
            for t in sorted(set(tenants) | set(tenant_led)):
                row = tenants.get(t) or {}
                led = tenant_led.get(t) or {}
                gp = led.get("goodput_fraction")
                warm = led.get("warm_start_fraction")
                phase_chip = led.get("phase_chip_s") or {}
                body.append(
                    f"<tr><td>{html.escape(t)}</td>"
                    f"<td>{row.get('used', 0)}/"
                    f"{row.get('quota') or '∞'}</td>"
                    f"<td>{(f'{float(gp):.1%}' if gp is not None else '—')}"
                    f"</td>"
                    f"<td>{phase_chip.get('train', 0)}</td>"
                    f"<td>{led.get('held_chip_s', 0)}</td>"
                    f"<td>{led.get('lost_preempted_chip_s', 0)}</td>"
                    f"<td>{(f'{float(warm):.0%}' if warm is not None else '—')}"
                    f"</td></tr>")
            body.append("</table>")
        body.append("<h2>jobs</h2>"
                    "<table border=1 cellpadding=4><tr><th>job</th>"
                    "<th>tenant</th><th>pri</th><th>state</th>"
                    "<th>hosts</th><th>wait</th><th>app / held</th>"
                    "</tr>")
        for row in snap.get("jobs", []):
            app = str(row.get("app_id") or "")
            app_cell = (f"<a href='/jobs/{html.escape(app)}'>"
                        f"{html.escape(app)}</a>") if app else \
                html.escape(str(row.get("held") or row.get("denial")
                                or ""))
            wait = row.get("wait_s")
            body.append(
                f"<tr><td>{html.escape(str(row.get('job')))}</td>"
                f"<td>{html.escape(str(row.get('tenant')))}</td>"
                f"<td>{row.get('priority', 0)}</td>"
                f"<td>{html.escape(str(row.get('state')))}</td>"
                f"<td>{row.get('hosts', 0)}/"
                f"{row.get('hosts_requested', '?')}</td>"
                f"<td>{(f'{wait:.1f}s' if wait is not None else '')}</td>"
                f"<td>{app_cell}</td></tr>")
        body.append("</table>")
        if prom:
            body.append("<h2>tony_fleet_* exposition</h2><pre>"
                        + html.escape(prom) + "</pre>")
        self._send_html(req, "".join(body))

    def _whatif_view(self, req, query: Dict[str, List[str]],
                     as_json: bool) -> None:
        """Counterfactual replay of the recorded fleet journal
        (fleet/simulator.py): ``/whatif?quota=tenant=4&pool=2x8&
        priority=job=10&set=k=v&sweep=k=a,b,c``. Always recomputed —
        the journal grows while the daemon lives, and each query is a
        different experiment; the 50-job scale this targets re-folds in
        well under a second (BENCH_WHATIF budget: 5 s)."""
        if not self.fleet_dir:
            return self._send(req, 404, "text/plain",
                              b"no fleet dir configured or discovered")
        from tony_tpu.fleet import simulator as fsim

        try:
            report = fsim.whatif_from_dir(
                self.fleet_dir, sets=query.get("set"),
                quotas=query.get("quota"),
                pool=(query.get("pool") or [""])[0] or None,
                priorities=query.get("priority"),
                sweeps=query.get("sweep"))
        except ValueError as e:
            return self._send(req, 400, "text/plain",
                              f"whatif: {e}".encode())
        except Exception as e:  # noqa: BLE001 — view stays up
            return self._send(req, 404, "text/plain",
                              f"whatif unavailable: {e}".encode())
        if as_json:
            return self._send_json(req, report)
        body = [f"<h1>fleet whatif — "
                f"{html.escape(str(report.get('journal')))}</h1>",
                "<p><a href='/fleet'>fleet</a> — recorded state. "
                "Query params: <code>quota=tenant=N</code>, "
                "<code>pool=SxH</code>, <code>priority=job=P</code>, "
                "<code>set=key=value</code>, "
                "<code>sweep=key=a,b,c</code> (repeatable).</p>"]
        par = report.get("parity") or {}
        if par.get("ok"):
            body.append("<p><b>parity: OK</b> — the recorded sequence "
                        "reproduces bit-for-bit; counterfactuals are "
                        "trustworthy</p>")
        elif not par.get("supported"):
            body.append(f"<p><b>parity: skipped</b> — "
                        f"{html.escape(str(par.get('reason', '')))}</p>")
        else:
            gate = "grant/preempt gate holds" if par.get("gate_ok") \
                else "grant/preempt gate BROKEN"
            body.append(f"<p><b>parity: "
                        f"{html.escape(json.dumps(par.get('mismatch_counts')))}"
                        f"</b> — {gate}</p>")
        rec = (report.get("recorded") or {}).get("metrics") or {}
        base = (report.get("base") or {}).get("metrics") or {}
        cfs = report.get("counterfactuals") or []
        keys = [k for k in fsim._TABLE_KEYS if k in rec or k in base]
        body.append("<table border=1 cellpadding=4><tr><th>metric</th>"
                    "<th>recorded</th><th>sim-base</th>"
                    + "".join(f"<th>{html.escape(c['label'])}</th>"
                              for c in cfs) + "</tr>")
        for k in keys:
            cells = ""
            for c in cfs:
                entry = (c.get("diff") or {}).get(k) or {}
                v = entry.get("counterfactual",
                              (c.get("metrics") or {}).get(k))
                mark = ""
                if entry.get("improves") is True:
                    mark = " ✓"
                elif entry.get("improves") is False:
                    mark = " ✗"
                cells += f"<td>{html.escape(fsim._cell(v))}{mark}</td>"
            body.append(f"<tr><td>{html.escape(k)}</td>"
                        f"<td>{html.escape(fsim._cell(rec.get(k)))}</td>"
                        f"<td>{html.escape(fsim._cell(base.get(k)))}</td>"
                        + cells + "</tr>")
        body.append("</table>")
        for c in cfs:
            removed = c.get("holds_removed") or []
            if not removed:
                continue
            body.append(f"<h2>{html.escape(c['label'])} — holds "
                        f"removed</h2><ul>")
            for h in removed:
                blocking = ", ".join(h.get("was_blocking") or []) or "—"
                body.append(
                    f"<li>tenant <b>{html.escape(h['tenant'])}</b>: "
                    f"{h['removed_s']}s of "
                    f"{html.escape(h['hold'].replace('_s', ''))} "
                    f"(was blocking: {html.escape(blocking)})</li>")
            body.append("</ul>")
        self._send_html(req, "".join(body))

    def _job_alerts(self, job_id: str) -> Dict[str, str]:
        """Final journaled alert state per rule (REC_ALERT fold) for one
        job. Live jobs bypass the TTL cache — their journal grows
        between requests, the same staleness contract as _events;
        finished jobs keep the cache."""
        if not self._job_live(job_id):
            hit = self.cache.get("alerts", job_id)
            if hit is not None:
                return hit
        job_dir = self._job_dir(job_id)
        if job_dir is None:
            return {}
        path = os.path.join(job_dir, constants.JOURNAL_FILE)
        alerts: Dict[str, str] = {}
        if os.path.exists(path):
            from tony_tpu.coordinator import journal as cjournal
            try:
                alerts = dict(cjournal.replay(path).alerts)
            except Exception as e:  # noqa: BLE001 — view stays up
                log.debug("alert replay failed for %s: %s", job_id, e)
        if not self._job_live(job_id):
            self.cache.put("alerts", job_id, alerts)
        return alerts

    def _alerts_view(self, req, as_json: bool) -> None:
        """The firing-state rollup: fleet-scope rules (live from the
        daemon's engine, or the REC_FLEET_ALERT fold of a dead one)
        plus every job's journaled alert state — the portal face of
        `tony-tpu alerts` / `tony-tpu fleet alerts`."""
        fleet: Optional[dict] = None
        if self.fleet_dir:
            client = self._fleet_client()
            if client is not None:
                try:
                    fleet = client.alerts()
                except Exception:  # noqa: BLE001 — fall back to replay
                    fleet = None
                finally:
                    client.close()
            if fleet is None:
                from tony_tpu.fleet import journal as fjournal
                try:
                    st = fjournal.replay(os.path.join(
                        self.fleet_dir, constants.FLEET_JOURNAL_FILE))
                    fleet = {"scope": "fleet", "offline": True,
                             "alerts": [{"rule": r, "state": s}
                                        for r, s
                                        in sorted(st.alerts.items())]}
                except Exception as e:  # noqa: BLE001
                    log.debug("fleet alert replay failed: %s", e)
        jobs = {job_id: self._job_alerts(job_id)
                for job_id in sorted(
                    history.list_job_dirs(self.history_root))}
        jobs = {j: a for j, a in jobs.items() if a}
        if as_json:
            return self._send_json(req, {"fleet": fleet, "jobs": jobs})
        body = ["<h1>alerts</h1>"]
        if fleet is not None:
            body.append("<h2>fleet</h2>")
            if fleet.get("degraded"):
                body.append("<p><b>evaluation DEGRADED</b> — disabled "
                            "after a fault; restart the daemon to "
                            "re-arm</p>")
            if fleet.get("offline"):
                body.append("<p>(journal replay — no live daemon)</p>")
            rows = fleet.get("alerts") or []
            if rows:
                body.append("<table border=1 cellpadding=4><tr>"
                            "<th>rule</th><th>state</th><th>severity"
                            "</th><th>value</th><th>series</th></tr>")
                for r in rows:
                    state = str(r.get("state", "?"))
                    cell = f"<b>{html.escape(state)}</b>" \
                        if state == "firing" else html.escape(state)
                    v = r.get("value")
                    body.append(
                        f"<tr><td>{html.escape(str(r.get('rule')))}"
                        f"</td><td>{cell}</td>"
                        f"<td>{html.escape(str(r.get('severity', '')))}"
                        f"</td><td>{'' if v is None else f'{v:.4g}'}"
                        f"</td><td>{html.escape(str(r.get('series', '')))}"
                        f"</td></tr>")
                body.append("</table>")
            else:
                body.append("<p>no fleet alert transitions</p>")
        body.append("<h2>jobs</h2>")
        if not jobs:
            body.append("<p>no journaled alert transitions in any "
                        "job</p>")
        else:
            body.append("<table border=1 cellpadding=4><tr><th>job</th>"
                        "<th>rule</th><th>state</th></tr>")
            for job_id, alerts in jobs.items():
                a = html.escape(job_id)
                for rule, state in sorted(alerts.items()):
                    cell = f"<b>{html.escape(state)}</b>" \
                        if state == "firing" else html.escape(state)
                    body.append(
                        f"<tr><td><a href='/metrics/{a}'>{a}</a></td>"
                        f"<td>{html.escape(rule)}</td>"
                        f"<td>{cell}</td></tr>")
            body.append("</table>")
        self._send_html(req, "".join(body))

    def _config_view(self, req, job_id: str, as_json: bool) -> None:
        conf = self.cache.get("config", job_id)
        if conf is None:
            job_dir = self._job_dir(job_id)
            if job_dir is None:
                return self._send(req, 404, "text/plain", b"unknown job")
            path = os.path.join(job_dir, constants.FINAL_CONFIG_FILE)
            if not os.path.exists(path):
                return self._send(req, 404, "text/plain",
                                  b"no frozen config for job")
            with open(path, encoding="utf-8") as f:
                conf = json.load(f)
            self.cache.put("config", job_id, conf)
        if as_json:
            return self._send_json(req, conf)
        rows = "".join(
            f"<tr><td>{html.escape(str(k))}</td>"
            f"<td>{html.escape(str(v))}</td></tr>"
            for k, v in sorted(conf.items()))
        self._send_html(
            req, f"<h1>config — {html.escape(job_id)}</h1>"
                 f"<table border=1 cellpadding=4>"
                 f"<tr><th>key</th><th>value</th></tr>{rows}</table>")

    def _job_live(self, job_id: str) -> bool:
        """Still-running job: its dir holds only an .inprogress stream (no
        finalized history file yet)."""
        job_dir = self._job_dir(job_id)
        return job_dir is not None and \
            history.find_history_file(job_dir) is None

    def _events(self, job_id: str):
        # Cache bypass for IN-PROGRESS jobs: their event stream grows
        # between requests, and the live views (events, metrics,
        # liveness incidents) must never serve a snapshot up to
        # _CACHE_TTL_S stale. Finished jobs never change — they keep the
        # cache (the reference CacheWrapper behaviour).
        if self._job_live(job_id):
            return history.read_job_events(self.history_root, job_id)
        evs = self.cache.get("events", job_id)
        if evs is None:
            evs = history.read_job_events(self.history_root, job_id)
            if evs is not None:
                self.cache.put("events", job_id, evs)
        return evs

    def _events_view(self, req, job_id: str, as_json: bool) -> None:
        evs = self._events(job_id)
        if evs is None:
            return self._send(req, 404, "text/plain", b"unknown job")
        if as_json:
            return self._send_json(
                req, [dict(type=e.type, timestamp_ms=e.timestamp_ms,
                           payload=e.payload) for e in evs])
        rows = "".join(
            f"<tr><td>{e.timestamp_ms}</td><td>{html.escape(e.type)}</td>"
            f"<td><pre>{html.escape(json.dumps(e.payload, indent=1))}"
            f"</pre></td></tr>" for e in evs)
        self._send_html(
            req, f"<h1>events — {html.escape(job_id)}</h1>"
                 f"<table border=1 cellpadding=4><tr><th>ts</th><th>type"
                 f"</th><th>payload</th></tr>{rows}</table>")

    def _metrics_view(self, req, job_id: str, as_json: bool) -> None:
        """Per-task final metrics from TASK_FINISHED events: memory/HBM
        aggregates + the utilization signal (steps/s, duty cycle, MFU)
        derived by telemetry.step() — the operator's one-stop 'is this job
        actually using its chips' view (reference surfaced per-task GPU
        util via TaskMonitor, TaskMonitor.java:116-170)."""
        evs = self._events(job_id)
        if evs is None:
            return self._send(req, 404, "text/plain", b"unknown job")
        tasks = [(e.payload.get("task", "?"), e.payload.get("metrics", {}))
                 for e in evs if e.type == "TASK_FINISHED"]
        if as_json:
            return self._send_json(
                req, [dict(task=t, metrics=m) for t, m in tasks])
        cols = sorted({k for _, m in tasks for k in m})
        head = "".join(f"<th>{html.escape(c)}</th>" for c in cols)
        rows = "".join(
            "<tr><td>" + html.escape(t) + "</td>" + "".join(
                f"<td>{html.escape(self._fmt_metric(m.get(c)))}</td>"
                for c in cols) + "</tr>"
            for t, m in tasks)
        self._send_html(
            req, f"<h1>metrics — {html.escape(job_id)}</h1>"
                 + self._alert_banner(job_id)
                 + f"<table border=1 cellpadding=4><tr><th>task</th>"
                 f"{head}</tr>{rows}</table>"
                 + self._coord_section(job_id)
                 + self._liveness_incidents(evs))

    def _alert_banner(self, job_id: str) -> str:
        """Firing-alert banner for the per-job views: quiet unless the
        journal fold says a rule is firing right now (live job) or was
        left firing at death (evidence — see /diagnose)."""
        firing = sorted(r for r, s in self._job_alerts(job_id).items()
                        if s == "firing")
        if not firing:
            return ""
        return ("<p><b>alerts firing:</b> "
                + ", ".join(html.escape(r) for r in firing)
                + " (<a href='/alerts'>details</a>)</p>")

    def _coord_section(self, job_id: str) -> str:
        """Control-plane self-observation table for the metrics view:
        the coordinator's own tony_coord_*/tony_journal_* families out
        of the job's live exposition (coordinator/coordphases.py) — is
        the CONTROL PLANE keeping up, next to whether the tasks are."""
        job_dir = self._job_dir(job_id)
        if job_dir is None:
            return ""
        path = os.path.join(job_dir, constants.METRICS_PROM_FILE)
        try:
            with open(path, "r", encoding="utf-8") as f:
                text = f.read()
        except OSError:
            return ""
        rows = []
        for line in text.splitlines():
            if line.startswith("#"):
                continue
            if line.startswith(("tony_coord_", "tony_journal_records",
                                "tony_journal_bytes")):
                name, _, value = line.rpartition(" ")
                rows.append(f"<tr><td><code>{html.escape(name)}</code>"
                            f"</td><td>{html.escape(value)}</td></tr>")
        if not rows:
            return ""
        return ("<h2>control plane (coordinator self-observation)</h2>"
                "<table border=1 cellpadding=4><tr><th>series</th>"
                "<th>value</th></tr>" + "".join(rows) + "</table>")

    #: progress-liveness event types surfaced as incidents on the metrics
    #: view (coordinator/liveness.py verdicts).
    _LIVENESS_EVENTS = ("TASK_HUNG", "TASK_STRAGGLER",
                        "TASK_PROGRESS_UNINSTRUMENTED")

    def _liveness_incidents(self, evs) -> str:
        """Hang/straggler incident table for the metrics view: the 'why
        did this job restart / crawl' answer next to the utilization
        numbers (full payloads — including the stack-dump excerpt riding
        the hang-kill TASK_FINISHED — stay in the events view)."""
        incidents = [e for e in evs if e.type in self._LIVENESS_EVENTS]
        if not incidents:
            return ""
        rows = "".join(
            f"<tr><td>{e.timestamp_ms}</td>"
            f"<td>{html.escape(e.type)}</td>"
            f"<td>{html.escape(str(e.payload.get('task', '?')))}</td>"
            f"<td><pre>{html.escape(json.dumps({k: v for k, v in e.payload.items() if k not in ('task', 'session_id')}, indent=1))}"
            f"</pre></td></tr>" for e in incidents)
        return (f"<h2>liveness incidents</h2>"
                f"<table border=1 cellpadding=4><tr><th>ts</th><th>type"
                f"</th><th>task</th><th>detail</th></tr>{rows}</table>")

    @staticmethod
    def _fmt_metric(v) -> str:
        if v is None:
            return ""
        if isinstance(v, float):
            return f"{v:,.4g}"
        return str(v)

    def _prom_view(self, req) -> None:
        """Prometheus scrape endpoint: concatenate the exposition files
        each live job's coordinator keeps fresh in its job dir
        (metrics.prom, tony.metrics.export-interval-s cadence), merged by
        metric family so HELP/TYPE lines stay unique and grouped. Never
        cached — a scrape must see the current write."""
        inter = os.path.join(self.history_root,
                             constants.HISTORY_INTERMEDIATE)
        families: Dict[str, Dict[str, List[str]]] = {}
        order: List[str] = []
        if os.path.isdir(inter):
            for app in sorted(os.listdir(inter)):
                path = os.path.join(inter, app, constants.METRICS_PROM_FILE)
                try:
                    with open(path, encoding="utf-8") as f:
                        text = f.read()
                except OSError:
                    continue
                fam = None
                for line in text.splitlines():
                    if line.startswith("# "):
                        parts = line.split(None, 3)
                        name = parts[2] if len(parts) > 2 else ""
                        fam = families.setdefault(
                            name, {"meta": [], "samples": []})
                        if name not in order:
                            order.append(name)
                        if line not in fam["meta"]:
                            fam["meta"].append(line)
                    elif line.strip() and fam is not None:
                        fam["samples"].append(line)
        lines: List[str] = []
        for name in order:
            lines.extend(families[name]["meta"])
            lines.extend(families[name]["samples"])
        body = ("\n".join(lines) + "\n") if lines \
            else "# no live jobs exporting metrics\n"
        self._send(req, 200,
                   "text/plain; version=0.0.4; charset=utf-8",
                   body.encode())

    def _trace_view(self, req, job_id: str, as_json: bool) -> None:
        """Per-job trace timeline from the span log the coordinator keeps
        next to the jhist stream. JSON = Chrome/Perfetto trace_events
        (same payload as `tony-tpu trace`); HTML = a simple Gantt of the
        spans, newest-run-friendly for 'what is the launch path doing'
        incident reads. Live jobs bypass the cache like events do."""
        from tony_tpu import tracing

        job_dir = self._job_dir(job_id)
        if job_dir is None:
            return self._send(req, 404, "text/plain", b"unknown job")
        path = os.path.join(job_dir, constants.TRACE_FILE)
        if not os.path.exists(path):
            return self._send(req, 404, "text/plain",
                              b"no trace recorded for job")
        payload = None
        if not self._job_live(job_id):
            payload = self.cache.get("trace", job_id)
        if payload is None:
            payload = tracing.to_trace_events(tracing.load_records(path))
            if not self._job_live(job_id):
                self.cache.put("trace", job_id, payload)
        if as_json:
            return self._send_json(req, payload)
        spans = [e for e in payload["traceEvents"] if e.get("ph") == "X"]
        if not spans:
            return self._send_html(
                req, f"<h1>trace — {html.escape(job_id)}</h1>"
                     f"<p>no complete spans yet</p>")
        t0 = min(e["ts"] for e in spans)
        t1 = max(e["ts"] + e.get("dur", 0) for e in spans)
        total = max(t1 - t0, 1)
        rows = []
        for e in sorted(spans, key=lambda s: s["ts"]):
            left = 100.0 * (e["ts"] - t0) / total
            width = max(100.0 * e.get("dur", 0) / total, 0.15)
            task = str(e.get("args", {}).get("task", "") or
                       e.get("cat", ""))
            rows.append(
                f"<tr><td>{html.escape(e['name'])}</td>"
                f"<td>{html.escape(task)}</td>"
                f"<td>{(e['ts'] - t0) / 1e3:,.1f}</td>"
                f"<td>{e.get('dur', 0) / 1e3:,.1f}</td>"
                f"<td style='width:50%'><div style='margin-left:"
                f"{left:.2f}%;width:{width:.2f}%;background:#4a90d9;"
                f"height:10px'></div></td></tr>")
        unclosed = payload.get("unclosedSpans", [])
        warn = (f"<p><b>{len(unclosed)} unclosed span(s):</b> "
                f"{html.escape(', '.join(unclosed))}</p>" if unclosed
                else "")
        self._send_html(
            req, f"<h1>trace — {html.escape(job_id)}</h1>"
                 f"<p>trace {html.escape(str(payload.get('traceId', '')))}"
                 f" · {len(spans)} spans · {total / 1e3:,.1f} ms"
                 f" · <a href='/trace/{html.escape(job_id)}?format=json'>"
                 f"Perfetto JSON</a></p>{warn}"
                 f"<table border=1 cellpadding=3 width='100%'>"
                 f"<tr><th>span</th><th>task</th><th>start ms</th>"
                 f"<th>dur ms</th><th>timeline</th></tr>"
                 + "".join(rows) + "</table>")

    def _log_paths(self, job_id: str) -> List[Tuple[str, str]]:
        """(task, path) pairs from the job's own TASK_FINISHED events — the
        only paths this server will ever read (no caller-supplied paths)."""
        evs = self._events(job_id) or []
        out: List[Tuple[str, str]] = []
        for e in evs:
            if e.type == "TASK_FINISHED":
                for p in e.payload.get("logs", []):
                    out.append((e.payload.get("task", "?"), p))
        return out

    def _logs_view(self, req, job_id: str, as_json: bool) -> None:
        pairs = self._log_paths(job_id)
        if as_json:
            return self._send_json(
                req, [dict(task=t, path=p,
                           url=f"/logfile/{job_id}/{i}")
                      for i, (t, p) in enumerate(pairs)])
        items = "".join(
            f"<li>{html.escape(t)}: "
            f"<a href='/logfile/{html.escape(job_id)}/{i}'>"
            f"{html.escape(os.path.basename(p))}</a></li>"
            for i, (t, p) in enumerate(pairs))
        body = f"<ul>{items}</ul>" if items else "<p>no logs recorded</p>"
        self._send_html(
            req, f"<h1>logs — {html.escape(job_id)}</h1>{body}")

    def _profiles_view(self, req, job_id: str, as_json: bool) -> None:
        """Profiler traces captured into <job_dir>/profile by the chief
        (tony_tpu/profiler.py; SURVEY.md §5 tracing). Listed by trace-
        window name; the files themselves are TensorBoard/Perfetto input,
        so the portal points at paths rather than rendering."""
        job_dir = self._job_dir(job_id)
        if job_dir is None:
            return self._send(req, 404, "text/plain", b"unknown job")
        root = os.path.join(job_dir, "profile")
        traces = []
        if os.path.isdir(root):
            for name in sorted(os.listdir(root)):
                p = os.path.join(root, name)
                n_files = sum(len(fs) for _, _, fs in os.walk(p))
                traces.append(dict(name=name, path=p, files=n_files))
        if as_json:
            return self._send_json(req, traces)
        items = "".join(
            f"<li>{html.escape(t['name'])} — {t['files']} file(s) at "
            f"<code>{html.escape(t['path'])}</code></li>" for t in traces)
        body = f"<ul>{items}</ul>" if items else "<p>no traces captured</p>"
        self._send_html(
            req, f"<h1>profiler traces — {html.escape(job_id)}</h1>{body}")

    def _logfile_view(self, req, job_id: str, index: int,
                      query: Optional[Dict[str, list]] = None) -> None:
        """Tail of one recorded task log. Seek-based (utils/logs.py —
        a multi-GB log costs only the requested tail, never a whole-file
        read into memory); ``?tail=N`` overrides the byte count."""
        from tony_tpu.utils import logs as logutil

        pairs = self._log_paths(job_id)
        if not 0 <= index < len(pairs):
            return self._send(req, 404, "text/plain", b"no such log")
        path = pairs[index][1]
        tail_bytes = logutil.DEFAULT_TAIL_BYTES
        raw = (query or {}).get("tail", [""])[0]
        if raw:
            try:
                tail_bytes = max(0, int(raw))
            except ValueError:
                return self._send(req, 400, "text/plain",
                                  b"bad ?tail= value (bytes expected)")
        try:
            data = logutil.tail_file(path, tail_bytes)
        except OSError:
            return self._send(req, 404, "text/plain",
                              b"log file no longer present")
        self._send(req, 200, "text/plain; charset=utf-8", data)

    def _diagnose_view(self, req, job_id: str, as_json: bool) -> None:
        """Automatic failure diagnosis (tony_tpu/diagnosis/): serve the
        coordinator-written incident.json for finished jobs; compute a
        PROVISIONAL read live for running ones (never cached — a live
        diagnosis must track the job). HTML and JSON from the same
        document the CLI renders."""
        from tony_tpu import diagnosis

        job_dir = self._job_dir(job_id)
        if job_dir is None:
            return self._send(req, 404, "text/plain", b"unknown job")
        incident = None
        if not self._job_live(job_id):
            incident = diagnosis.load_incident(
                os.path.join(job_dir, constants.INCIDENT_FILE))
        if incident is None:
            incident = diagnosis.diagnose_job_dir(
                job_dir, app_id=job_id,
                provisional=self._job_live(job_id))
        if as_json:
            return self._send_json(req, incident)
        self._send_html(req, diagnosis.render_html(incident))

    # -- plumbing --------------------------------------------------------
    def _send(self, req, code: int, ctype: str, body: bytes) -> None:
        req.send_response(code)
        req.send_header("Content-Type", ctype)
        req.send_header("Content-Length", str(len(body)))
        req.end_headers()
        req.wfile.write(body)

    def _send_html(self, req, body: str) -> None:
        page = ("<!doctype html><html><head><title>tony-tpu history</title>"
                "</head><body><p><a href='/'>&larr; jobs</a></p>"
                f"{body}</body></html>")
        self._send(req, 200, "text/html; charset=utf-8", page.encode())

    def _send_json(self, req, obj) -> None:
        self._send(req, 200, "application/json",
                   json.dumps(obj, indent=1).encode())


def main(argv=None) -> int:
    """``python -m tony_tpu.portal --history-root ... [--port N]``."""
    import argparse

    from tony_tpu.conf import keys as K
    from tony_tpu.conf.config import TonyTpuConfig

    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser(prog="tony-tpu-portal")
    p.add_argument("--history-root", required=True)
    p.add_argument("--port", type=int, default=None)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--token", default=os.environ.get(
        "TONY_PORTAL_TOKEN", ""),
        help="require 'Authorization: Bearer <token>' on every request "
             "(default: $TONY_PORTAL_TOKEN; empty = open — keep the bind "
             "host local then)")
    p.add_argument("--tls-cert", default="",
                   help="PEM cert path: serve HTTPS (pair with --tls-key)")
    p.add_argument("--tls-key", default="",
                   help="PEM private-key path for --tls-cert")
    p.add_argument("--fleet-dir", default="",
                   help="fleet daemon dir for the /fleet view (default: "
                        "auto-discovered when the history root lives "
                        "inside a fleet dir)")
    args = p.parse_args(argv)
    if bool(args.tls_cert) != bool(args.tls_key):
        p.error("--tls-cert and --tls-key must be set together")
    conf = TonyTpuConfig()
    port = args.port if args.port is not None \
        else conf.get_int(K.PORTAL_PORT, 19886)
    srv = PortalServer(
        args.history_root, port=port, host=args.host,
        mover_interval_s=conf.get_int(K.HISTORY_MOVER_INTERVAL_S, 300),
        purger_interval_s=conf.get_int(K.HISTORY_PURGER_INTERVAL_S, 3600),
        retention_days=conf.get_int(K.HISTORY_RETENTION_DAYS, 30),
        token=args.token, tls_cert=args.tls_cert, tls_key=args.tls_key,
        fleet_dir=args.fleet_dir)
    srv.start()
    log.info("portal serving %s at %s", args.history_root, srv.url)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()
    return 0
