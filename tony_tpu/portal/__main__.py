import sys

from tony_tpu.portal.server import main

if __name__ == "__main__":
    sys.exit(main())
