from tony_tpu.portal.server import PortalServer

__all__ = ["PortalServer"]
