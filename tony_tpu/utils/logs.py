"""Shared log-tail + excerpt extraction helpers.

One seek-based tail used by everything that reads task logs — the portal
``/logfile`` view, the diagnosis collector, the coordinator's stack-dump
capture. The previous pattern (``open(path).read()[-N:]``) slurped whole
multi-GB task logs into memory to keep the last megabyte; ``tail_file``
seeks instead, so cost is bounded by the requested tail regardless of
file size.

The extractors pull the two excerpt shapes incident diagnosis cares
about out of a log tail:

- ``extract_traceback``: the LAST complete Python traceback (a crashing
  user process may log earlier, caught-and-retried tracebacks; the one
  that killed it is the final one);
- ``extract_stack_dump``: the faulthandler all-thread dump the hung-task
  diagnostics pass writes (``Thread 0x...`` / ``Current thread 0x...``
  markers — Python's own format, telemetry.install_stack_dump_handler).
"""

from __future__ import annotations

import os
import re
from typing import Optional

#: default tail kept by log views / collectors when the caller gives none
DEFAULT_TAIL_BYTES = 1_000_000


def tail_file(path: str, max_bytes: int = DEFAULT_TAIL_BYTES) -> bytes:
    """Last ``max_bytes`` of ``path``, read with a seek — never the whole
    file. Raises OSError like open() would (callers decide whether a
    missing log is an error or just absent evidence)."""
    max_bytes = max(0, int(max_bytes))
    with open(path, "rb") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        f.seek(max(0, size - max_bytes))
        return f.read(max_bytes) if max_bytes else b""


def tail_text(path: str, max_bytes: int = DEFAULT_TAIL_BYTES
              ) -> Optional[str]:
    """``tail_file`` decoded utf-8/replace; None when unreadable — the
    diagnosis collector treats a purged log as missing evidence, not a
    collection failure."""
    try:
        return tail_file(path, max_bytes).decode("utf-8", "replace")
    except OSError:
        return None


_TRACEBACK_START = "Traceback (most recent call last):"
#: the exception line closing a traceback block: "Name: msg" or bare
#: "Name" at column 0 (frames and source lines are indented).
_EXC_LINE = re.compile(r"^[A-Za-z_][\w.]*(Error|Exception|Interrupt|Exit|"
                       r"Warning|Fault)?\b.*$")


def extract_traceback(text: str, max_chars: int = 8192) -> str:
    """The LAST complete Python traceback in ``text`` ('' when none).

    Scans from the final "Traceback (most recent call last):" marker and
    keeps lines through the unindented exception line that terminates the
    block (chained tracebacks — "During handling..." — are kept whole by
    restarting from the FIRST marker of the final chain)."""
    idx = text.rfind(_TRACEBACK_START)
    if idx < 0:
        return ""
    # Walk back over a chained-exception group so "The above exception
    # was the direct cause" context survives in the excerpt.
    while True:
        prev = text.rfind(_TRACEBACK_START, 0, idx)
        if prev < 0:
            break
        between = text[prev + len(_TRACEBACK_START):idx]
        if "direct cause" in between or "During handling" in between:
            idx = prev
            continue
        break
    lines = text[idx:].splitlines()
    out = []
    for i, line in enumerate(lines):
        out.append(line)
        if i == 0 or not line or line[0] in (" ", "\t"):
            continue
        if line.startswith(_TRACEBACK_START) or "direct cause" in line \
                or "During handling" in line:
            continue
        if _EXC_LINE.match(line):
            # Unindented exception line ends the block — unless a
            # chained traceback follows (blank lines + the "direct
            # cause"/"During handling" bridge sit between the blocks).
            rest = "\n".join(lines[i + 1:i + 6])
            if _TRACEBACK_START not in rest and "direct cause" not in rest \
                    and "During handling" not in rest:
                break
    return "\n".join(out)[:max_chars]


def extract_stack_dump(text: str, max_chars: int = 4096) -> str:
    """Faulthandler all-thread dump excerpt ('' when none): from the
    FIRST thread marker in ``text`` so the excerpt spans the whole dump,
    not just its final thread block (same logic the coordinator uses on
    a hang kill), trimmed at the first line that is not part of the dump
    (frames, thread headers) so trailing log noise stays out."""
    idx = text.find("Thread 0x")
    cur = text.find("Current thread 0x")
    if idx < 0 or (0 <= cur < idx):
        idx = cur
    if idx < 0:
        return ""
    out = []
    for line in text[idx:].splitlines():
        if line and not line.startswith(("Thread 0x", "Current thread 0x",
                                         " ", "\t")):
            break
        out.append(line)
    return "\n".join(out).rstrip()[:max_chars]
