"""Resource localization: the ``SRC[::NAME][#archive]`` grammar + staging.

Reference model: ``LocalizableResource.java:20-30`` — ``SOURCE::PATH_IN_
CONTAINER#archive``, only SOURCE required; NAME defaults to the source
basename; ``#archive`` marks the file for unpacking at localization time
(parse :75-102). Client-side staging replaces the HDFS upload
(``TonyClient.processFinalTonyConf`` :189-228, venv zip included); executor-
side localization replaces YARN's container localizer: each resource lands
in the task working directory under NAME, archives are unpacked into a
directory called NAME (YARN archive semantics).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import shutil
import threading
from tony_tpu.storage.store import is_url
from typing import Dict, List, Optional

log = logging.getLogger(__name__)

ARCHIVE_SUFFIX = "#archive"
DIVIDER = "::"

#: per-workdir record of what was localized and from which content —
#: the skip index for re-localization into the SAME workdir (retry
#: epochs reuse task dirs; warm-pool hosts reuse cache dirs).
MANIFEST_FILE = ".tony-localized.json"

#: resources/specs localized concurrently per call (bounded: the wins are
#: store-fetch latency overlap and copy pipelining, not raw thread count)
MAX_LOCALIZE_WORKERS = 4


@dataclasses.dataclass(frozen=True)
class LocalizableResource:
    source: str
    name: str
    archive: bool

    @classmethod
    def parse(cls, spec: str) -> "LocalizableResource":
        """Parse ``SRC[::NAME][#archive]`` (reference parse :75-102)."""
        s = spec.strip()
        archive = s.lower().endswith(ARCHIVE_SUFFIX)
        if archive:
            s = s[: -len(ARCHIVE_SUFFIX)]
        parts = s.split(DIVIDER)
        if len(parts) > 2 or not parts[0]:
            raise ValueError(f"failed to parse resource: {spec!r}")
        name = parts[1] if len(parts) == 2 and parts[1] \
            else os.path.basename(parts[0].rstrip("/"))
        return cls(source=parts[0], name=name, archive=archive)

    def unparse(self) -> str:
        out = self.source
        if self.name != os.path.basename(self.source.rstrip("/")):
            out += DIVIDER + self.name
        if self.archive:
            out += ARCHIVE_SUFFIX
        return out


def stage_resources(specs: List[str], stage_dir: str, store=None,
                    store_prefix: str = "") -> List[str]:
    """Client side: copy each resource into the staging area (the HDFS
    upload analogue) and return rewritten specs pointing at the staged
    copies, annotations preserved. With ``store``/``store_prefix`` the
    staged copies are PUT to the object store and the rewritten sources
    are store URLs (``tony_tpu.storage``); sources that are already store
    URLs pass through untouched.

    Resources stage CONCURRENTLY (each lands in its own index-keyed
    directory/prefix, so no two copies can collide); existence is
    validated up front in the calling thread, and the returned specs keep
    submission order regardless of completion order."""
    parsed = [LocalizableResource.parse(spec) for spec in specs]
    for spec, r in zip(specs, parsed):
        if not is_url(r.source) and not os.path.exists(r.source):
            raise FileNotFoundError(
                f"resource {r.source!r} (from {spec!r}) does not exist")

    def stage_one(i: int) -> str:
        r, spec = parsed[i], specs[i]
        if is_url(r.source):
            return spec.strip()
        base = os.path.basename(r.source.rstrip("/"))
        if store is not None:
            from tony_tpu.storage.store import join as ujoin

            url = ujoin(store_prefix, str(i), base)
            if os.path.isdir(r.source):
                store.put_tree(r.source, url)
            else:
                store.put_file(r.source, url)
            return LocalizableResource(url, r.name, r.archive).unparse()
        dest_dir = os.path.join(stage_dir, str(i))
        os.makedirs(dest_dir, exist_ok=True)
        staged = os.path.join(dest_dir, base)
        if os.path.isdir(r.source):
            shutil.copytree(r.source, staged, dirs_exist_ok=True)
        else:
            shutil.copy2(r.source, staged)
        return LocalizableResource(staged, r.name, r.archive).unparse()

    return _run_ordered(stage_one, len(specs))


def _run_ordered(fn, n: int) -> List[str]:
    """Run ``fn(0..n-1)`` over a bounded thread pool, results in index
    order; the first failure re-raises. Serial for n<=1 (no pool tax on
    the common single-resource case)."""
    if n <= 0:
        return []
    if n == 1:
        return [fn(0)]
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(
            max_workers=min(MAX_LOCALIZE_WORKERS, n),
            thread_name_prefix="tony-localize") as pool:
        return [f.result() for f in [pool.submit(fn, i) for i in range(n)]]


def file_content_hash(path: str) -> str:
    """sha256 of a file's bytes — the localization skip key."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def tree_signature(path: str) -> str:
    """Cheap content signature for a directory tree: sha256 over the
    sorted (relpath, size, mtime_ns) triples. Not byte-exact like
    file_content_hash (hashing every byte of a big bundle would cost as
    much as the copy it tries to skip), but any file add/remove/rewrite
    changes it — the false-skip window is a same-size same-mtime in-place
    edit, which no staging path here produces."""
    h = hashlib.sha256()
    for root, dirs, files in os.walk(path):
        dirs.sort()
        for name in sorted(files):
            p = os.path.join(root, name)
            try:
                st = os.stat(p)
            except OSError:
                continue
            rel = os.path.relpath(p, path)
            h.update(f"{rel}\0{st.st_size}\0{st.st_mtime_ns}\n".encode())
    return h.hexdigest()


def source_signature(source: str) -> str:
    """Skip key for a local source: content hash for files, tree
    signature for directories."""
    return tree_signature(source) if os.path.isdir(source) \
        else file_content_hash(source)


def load_manifest(workdir: str) -> Dict[str, str]:
    try:
        with open(os.path.join(workdir, MANIFEST_FILE),
                  encoding="utf-8") as f:
            data = json.load(f)
        return data if isinstance(data, dict) else {}
    except (OSError, ValueError):
        return {}


def save_manifest(workdir: str, manifest: Dict[str, str]) -> None:
    try:
        from tony_tpu.utils.durable import atomic_write

        # Durable, not just atomic: the manifest vouches for localized
        # content by hash/signature — it must never claim files whose own
        # writes a crash could still lose.
        atomic_write(os.path.join(workdir, MANIFEST_FILE),
                     json.dumps(manifest, sort_keys=True).encode("utf-8"))
    except OSError as e:  # the manifest is an optimization, never a failure
        log.debug("localization manifest write failed: %s", e)


def localize_resources(specs: List[str], workdir: str,
                       manifest: Optional[Dict[str, str]] = None
                       ) -> List[str]:
    """Executor side: place every staged resource into the task working dir
    under its container name; unpack archives into a directory named NAME
    (YARN ARCHIVE localization semantics; exercised by the reference e2e
    ``TestTonyE2E.java:322-340``). Store-URL sources are fetched through
    ``tony_tpu.storage`` first — a remote task host never dereferences a
    client-local path.

    Two cold-start levers since the parallel-localize change:

    - resources localize CONCURRENTLY (index-keyed fetch dirs + distinct
      target names make the copies independent; store-fetch latency and
      copy I/O overlap instead of queuing);
    - a CONTENT-HASH skip: each placed resource's source signature lands
      in ``.tony-localized.json``; a re-localization into the same
      workdir (retry epoch, pooled-host cache) with an unchanged source
      and a still-present target is a no-op. Store-URL sources are never
      skipped — their bytes live remotely and the URL embeds the job
      prefix anyway.
    """
    # A caller-provided manifest is shared state the CALLER persists (the
    # executor folds bundle/venv/resource entries into one file); without
    # one, this function owns the load/save round trip.
    own_manifest = manifest is None
    if manifest is None:
        manifest = load_manifest(workdir) if specs else {}
    lock = threading.Lock()

    def localize_one(i: int) -> str:
        r = LocalizableResource.parse(specs[i])
        source = r.source
        target = os.path.join(workdir, r.name)
        local_source = not (is_url(source)
                            and not source.startswith("file://"))
        if local_source:
            plain = source[len("file://"):] \
                if source.startswith("file://") else source
            sig = f"{r.name}|{'archive' if r.archive else 'copy'}|" \
                  f"{source_signature(plain)}"
            if manifest.get(r.name) == sig and os.path.exists(target):
                log.debug("localization skip (content unchanged): %s",
                          r.name)
                return target
            source = plain
        else:
            from tony_tpu.storage import get_store

            store = get_store(source)
            # Keyed by index: two resources may share a basename, and a
            # colliding get_tree(dirs_exist_ok) would silently merge them.
            fetched = os.path.join(workdir, ".fetch", str(i),
                                   os.path.basename(source.rstrip("/")))
            if store.isdir(source):
                store.get_tree(source, fetched)
            else:
                store.get_file(source, fetched)
            source = fetched
            sig = ""
        if r.archive:
            os.makedirs(target, exist_ok=True)
            shutil.unpack_archive(source, target)
        elif os.path.isdir(source):
            shutil.copytree(source, target, dirs_exist_ok=True)
        else:
            os.makedirs(os.path.dirname(target) or ".", exist_ok=True)
            shutil.copy2(source, target)
        if sig:
            with lock:
                manifest[r.name] = sig
        return target

    placed = _run_ordered(localize_one, len(specs))
    if specs and own_manifest:
        save_manifest(workdir, manifest)
    return placed
