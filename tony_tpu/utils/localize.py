"""Resource localization: the ``SRC[::NAME][#archive]`` grammar + staging.

Reference model: ``LocalizableResource.java:20-30`` — ``SOURCE::PATH_IN_
CONTAINER#archive``, only SOURCE required; NAME defaults to the source
basename; ``#archive`` marks the file for unpacking at localization time
(parse :75-102). Client-side staging replaces the HDFS upload
(``TonyClient.processFinalTonyConf`` :189-228, venv zip included); executor-
side localization replaces YARN's container localizer: each resource lands
in the task working directory under NAME, archives are unpacked into a
directory called NAME (YARN archive semantics).
"""

from __future__ import annotations

import dataclasses
import os
import shutil
from tony_tpu.storage.store import is_url
from typing import List

ARCHIVE_SUFFIX = "#archive"
DIVIDER = "::"


@dataclasses.dataclass(frozen=True)
class LocalizableResource:
    source: str
    name: str
    archive: bool

    @classmethod
    def parse(cls, spec: str) -> "LocalizableResource":
        """Parse ``SRC[::NAME][#archive]`` (reference parse :75-102)."""
        s = spec.strip()
        archive = s.lower().endswith(ARCHIVE_SUFFIX)
        if archive:
            s = s[: -len(ARCHIVE_SUFFIX)]
        parts = s.split(DIVIDER)
        if len(parts) > 2 or not parts[0]:
            raise ValueError(f"failed to parse resource: {spec!r}")
        name = parts[1] if len(parts) == 2 and parts[1] \
            else os.path.basename(parts[0].rstrip("/"))
        return cls(source=parts[0], name=name, archive=archive)

    def unparse(self) -> str:
        out = self.source
        if self.name != os.path.basename(self.source.rstrip("/")):
            out += DIVIDER + self.name
        if self.archive:
            out += ARCHIVE_SUFFIX
        return out


def stage_resources(specs: List[str], stage_dir: str, store=None,
                    store_prefix: str = "") -> List[str]:
    """Client side: copy each resource into the staging area (the HDFS
    upload analogue) and return rewritten specs pointing at the staged
    copies, annotations preserved. With ``store``/``store_prefix`` the
    staged copies are PUT to the object store and the rewritten sources
    are store URLs (``tony_tpu.storage``); sources that are already store
    URLs pass through untouched."""
    out: List[str] = []
    for i, spec in enumerate(specs):
        r = LocalizableResource.parse(spec)
        if is_url(r.source):
            out.append(spec.strip())
            continue
        if not os.path.exists(r.source):
            raise FileNotFoundError(
                f"resource {r.source!r} (from {spec!r}) does not exist")
        base = os.path.basename(r.source.rstrip("/"))
        if store is not None:
            from tony_tpu.storage.store import join as ujoin

            url = ujoin(store_prefix, str(i), base)
            if os.path.isdir(r.source):
                store.put_tree(r.source, url)
            else:
                store.put_file(r.source, url)
            out.append(LocalizableResource(url, r.name, r.archive).unparse())
            continue
        dest_dir = os.path.join(stage_dir, str(i))
        os.makedirs(dest_dir, exist_ok=True)
        staged = os.path.join(dest_dir, base)
        if os.path.isdir(r.source):
            shutil.copytree(r.source, staged, dirs_exist_ok=True)
        else:
            shutil.copy2(r.source, staged)
        out.append(LocalizableResource(staged, r.name, r.archive).unparse())
    return out


def localize_resources(specs: List[str], workdir: str) -> List[str]:
    """Executor side: place every staged resource into the task working dir
    under its container name; unpack archives into a directory named NAME
    (YARN ARCHIVE localization semantics; exercised by the reference e2e
    ``TestTonyE2E.java:322-340``). Store-URL sources are fetched through
    ``tony_tpu.storage`` first — a remote task host never dereferences a
    client-local path."""
    placed: List[str] = []
    for i, spec in enumerate(specs):
        r = LocalizableResource.parse(spec)
        source = r.source
        if is_url(source) and not source.startswith("file://"):
            from tony_tpu.storage import get_store

            store = get_store(source)
            # Keyed by index: two resources may share a basename, and a
            # colliding get_tree(dirs_exist_ok) would silently merge them.
            fetched = os.path.join(workdir, ".fetch", str(i),
                                   os.path.basename(source.rstrip("/")))
            if store.isdir(source):
                store.get_tree(source, fetched)
            else:
                store.get_file(source, fetched)
            source = fetched
        elif source.startswith("file://"):
            source = source[len("file://"):]
        target = os.path.join(workdir, r.name)
        if r.archive:
            os.makedirs(target, exist_ok=True)
            shutil.unpack_archive(source, target)
        elif os.path.isdir(source):
            shutil.copytree(source, target, dirs_exist_ok=True)
        else:
            os.makedirs(os.path.dirname(target) or ".", exist_ok=True)
            shutil.copy2(source, target)
        placed.append(target)
    return placed
