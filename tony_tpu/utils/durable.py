"""Crash-durable file primitives shared by the session journal, the event
stream, and the frozen-config artifact.

A coordinator can be SIGKILLed between any two instructions (that is the
whole premise of ``--recover``), so every write that recovery or the
history portal later depends on must be one of exactly two shapes:

- **atomic replace**: write a temp file in the SAME directory, fsync it,
  ``os.replace`` over the target, fsync the directory — a reader sees
  either the old bytes or the new bytes, never a torn mix
  (``atomic_write``/``durable_replace``);
- **append-only log**: appended records are fsync'd before the caller
  proceeds, and the READER tolerates a torn final record (the crash
  window between ``write`` and ``fsync``) by degrading to
  replay-of-prefix (``AppendLog``; readers: journal.replay,
  events.read_events).

POSIX note: ``os.replace`` is atomic on the same filesystem but the
RENAME itself is only durable once the parent directory is fsync'd —
skipping that step is how "the rename happened but vanished after the
power cut" bugs are born.
"""

from __future__ import annotations

import errno
import logging
import os
from typing import IO, Optional

from tony_tpu import faults

log = logging.getLogger(__name__)


class DurableWriteError(OSError):
    """A durable write (fsync'd append / atomic replace) FAILED — the
    bytes may not be on disk. ENOSPC/EIO on the write-ahead path must
    surface loudly (terminal INFRA verdict, daemon stop): proceeding as
    if the record landed is how recovery later resurrects state the
    rest of the cluster already saw retired. The committed prefix on
    disk stays intact — ``--recover`` replays it (readers tolerate a
    torn final record)."""

    def __init__(self, path: str, op: str, cause: BaseException) -> None:
        eno = cause.errno if isinstance(cause, OSError) and cause.errno \
            else errno.EIO
        super().__init__(eno, f"durable {op} failed for {path}: {cause}")
        self.path = path
        self.op = op


def fsync_dir(path: str) -> None:
    """fsync a DIRECTORY so renames/creates inside it survive a crash.
    Best-effort: some filesystems (and all of Windows) refuse O_RDONLY
    on directories — durability then degrades to the OS's own schedule,
    which is still no worse than the pre-helper behaviour."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write(path: str, data: bytes, mode: int = 0o644) -> None:
    """Atomically (re)place ``path`` with ``data``: temp file in the same
    directory → write → flush+fsync → rename → directory fsync.

    ``mode`` applies from the temp file's very first byte (no chmod-after
    window) — pass 0o600 for secret-bearing artifacts like the
    coordinator/pool address files, which carry the RPC auth token."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = os.path.join(d, f".{os.path.basename(path)}.tmp.{os.getpid()}")
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, mode)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        if faults.fire("disk.torn"):
            # The injected power-cut-at-rename shape: the temp file was
            # durable but the RENAME never landed — a reader still sees
            # the OLD bytes, and the caller must hear about it.
            raise OSError(errno.EIO, "injected torn rename (disk.torn)")
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    fsync_dir(d)


def durable_replace(src: str, dst: str) -> None:
    """``os.replace`` + directory fsync (same-directory renames like the
    in-progress → final history file flip)."""
    os.replace(src, dst)
    fsync_dir(os.path.dirname(os.path.abspath(dst)))


def fsync_path(path: str) -> None:
    """fsync an already-written file by path. For stream-written temp
    files (downloads, copies) promote with ``fsync_path(tmp)`` +
    ``durable_replace(tmp, dst)`` — the same two-fsync shape as
    ``atomic_write`` without buffering the payload in memory."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def fsync_file(f: IO) -> None:
    """flush + fsync an open file object; best-effort on exotic streams
    without a real descriptor (StringIO in tests)."""
    try:
        f.flush()
        os.fsync(f.fileno())
    except (OSError, ValueError, AttributeError):
        pass


class AppendLog:
    """fsync-per-append log file (the write-ahead journal's substrate).

    Every ``append`` returns only after the record is flushed AND
    fsync'd: a crash immediately after a state transition must find that
    transition on disk — otherwise replay resurrects pre-transition
    state and the recovered coordinator disagrees with the executors
    that already observed the transition over RPC.
    """

    def __init__(self, path: str) -> None:
        self.path = path
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        existed = os.path.exists(path)
        self._f: Optional[IO] = open(path, "ab")
        if not existed:
            # The file CREATION itself must survive a crash too.
            fsync_dir(d)

    def append(self, record: bytes) -> None:
        """Append + flush + fsync, STRICT: any failure raises
        DurableWriteError instead of pretending the record landed.
        A torn append (partial write, then the failure) is exactly the
        shape the journal readers already absorb — replay-of-prefix —
        so the committed records before it stay recoverable."""
        if self._f is None:
            raise ValueError(f"append log {self.path} is closed")
        try:
            faults.check("disk.full")
            if faults.fire("disk.torn"):
                self._f.write(record[:max(1, len(record) // 2)])
                self._f.flush()
                raise OSError(errno.EIO,
                              "injected torn append (disk.torn)")
            self._f.write(record)
            self._f.flush()
            os.fsync(self._f.fileno())
        except OSError as e:
            raise DurableWriteError(self.path, "append", e) from e

    def close(self) -> None:
        if self._f is not None:
            fsync_file(self._f)
            self._f.close()
            self._f = None
