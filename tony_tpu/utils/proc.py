"""Process execution helpers (reference ``Utils.executeShell`` :294-323)."""

from __future__ import annotations

import logging
import os
import signal
import subprocess
import time
from typing import Callable, Dict, Optional

log = logging.getLogger(__name__)


def execute_shell(command: str, timeout_s: float = 0,
                  env: Optional[Dict[str, str]] = None,
                  cwd: Optional[str] = None,
                  on_start: Optional[Callable[[subprocess.Popen], None]] = None,
                  ) -> int:
    """Run a shell command, inheriting stdout/stderr (container logs pattern,
    ``ApplicationMaster.java:1145-1147``). Returns the exit code; a timeout
    kills the whole process group and returns 137.

    The reference unsets MALLOC_ARENA_MAX before exec (``Utils.java:312``) —
    a YARN-ism we do not need; we instead leave JAX/XLA env untouched so
    the user process sees exactly what the runtime exported.
    """
    full_env = dict(os.environ)
    if env:
        full_env.update(env)
    log.info("executing: %s", command)
    proc = subprocess.Popen(
        ["/bin/sh", "-c", command], env=full_env, cwd=cwd,
        start_new_session=True)
    if on_start:
        on_start(proc)
    try:
        return proc.wait(timeout=timeout_s or None)
    except subprocess.TimeoutExpired:
        log.error("command timed out after %ss; killing process group",
                  timeout_s)
        try:
            os.killpg(proc.pid, signal.SIGTERM)
            time.sleep(1)
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        proc.wait()
        return 137


def poll_till_non_null(fn: Callable[[], Optional[object]],
                       interval_s: float = 3.0,
                       timeout_s: float = 0) -> Optional[object]:
    """Reference ``Utils.pollTillNonNull`` :91-145 — the executor's
    registration barrier poll."""
    deadline = time.monotonic() + timeout_s if timeout_s else None
    while True:
        result = fn()
        if result is not None:
            return result
        if deadline and time.monotonic() > deadline:
            return None
        time.sleep(interval_s)
