"""Process execution helpers (reference ``Utils.executeShell`` :294-323)."""

from __future__ import annotations

import logging
import os
import signal
import subprocess
import time
from typing import Callable, Dict, Optional

log = logging.getLogger(__name__)


def _group_has_live_member(pg: int) -> bool:
    """Any NON-ZOMBIE process left in group ``pg``? ``killpg(pg, 0)``
    alone cannot answer this: it succeeds while only zombies remain, and a
    TERM'd child whose parent hasn't reaped it yet IS a zombie — exactly
    the teardown window this function is called in (a coordinator killing
    an executor it owns polls nothing while it waits). Counting a
    zombie-only group as alive made every such kill burn its FULL grace
    window (measured: 15 s per failed-job teardown)."""
    try:
        entries = os.listdir("/proc")
    except OSError:
        return True     # no /proc: fall back to the killpg-only signal
    for entry in entries:
        if not entry.isdigit():
            continue
        try:
            with open(f"/proc/{entry}/stat") as f:
                # "pid (comm) state ppid pgrp ..." — comm may hold spaces/
                # parens; split after the LAST ')'.
                rest = f.read().rsplit(")", 1)[1].split()
            if int(rest[2]) == pg and rest[0] != "Z":
                return True
        except (OSError, ValueError, IndexError):
            continue
    return False


def kill_process_groups(pgids, grace_s: float = 0.0) -> None:
    """TERM → grace → KILL for one or more process groups. The building
    block of the teardown contract (reference stops containers with grace,
    ``ApplicationMaster.java:694-711``, and YARN's NM then reaps the whole
    container tree — with no NM, supervisors here must do the reaping).

    Safe on already-dead groups (ProcessLookupError = nothing left) and on
    pgids we cannot signal (PermissionError = not ours, e.g. after a
    pid-reuse race — skip rather than kill a stranger). The grace wait
    ends when every group member is dead OR a zombie (see
    ``_group_has_live_member``)."""
    alive = set()
    for pg in pgids:
        if not pg or pg <= 0:
            continue
        try:
            os.killpg(pg, signal.SIGTERM)
            alive.add(pg)
        except (ProcessLookupError, PermissionError):
            pass
    deadline = time.monotonic() + grace_s
    zombie_only = set()
    while alive and time.monotonic() < deadline:
        for pg in list(alive):
            try:
                os.killpg(pg, 0)
            except (ProcessLookupError, PermissionError):
                alive.discard(pg)
                continue
            if not _group_has_live_member(pg):
                # Stop WAITING on it, but still include it in the KILL
                # pass below: the /proc snapshot races a fork during the
                # grace window, and SIGKILL on a truly zombie-only group
                # is a free no-op.
                alive.discard(pg)
                zombie_only.add(pg)
        if alive:
            time.sleep(0.05)
    for pg in alive | zombie_only:
        try:
            os.killpg(pg, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass


def read_pgid_file(path: str) -> int:
    """Process-group id from a pidfile (``user.pgid`` contract —
    constants.USER_PGID_FILE); 0 when absent/corrupt."""
    try:
        with open(path) as f:
            return int(f.read().strip() or 0)
    except (OSError, ValueError):
        return 0


def execute_shell(command: str, timeout_s: float = 0,
                  env: Optional[Dict[str, str]] = None,
                  cwd: Optional[str] = None,
                  on_start: Optional[Callable[[subprocess.Popen], None]] = None,
                  ) -> int:
    """Run a shell command, inheriting stdout/stderr (container logs pattern,
    ``ApplicationMaster.java:1145-1147``). Returns the exit code (128+N for
    death by signal N); a timeout kills the whole process group and returns
    137. The command runs in its OWN session/process group so a supervisor
    can signal the user tree without shooting itself — the group id (=child
    pid) is observable via ``on_start`` and must be reaped by the caller's
    teardown (see ``kill_process_groups``); any stragglers the command
    leaves in its group are reaped here after it exits.

    The reference unsets MALLOC_ARENA_MAX before exec (``Utils.java:312``) —
    a YARN-ism we do not need; we instead leave JAX/XLA env untouched so
    the user process sees exactly what the runtime exported.
    """
    full_env = dict(os.environ)
    if env:
        full_env.update(env)
    log.info("executing: %s", command)
    proc = subprocess.Popen(
        ["/bin/sh", "-c", command], env=full_env, cwd=cwd,
        start_new_session=True)
    if on_start:
        on_start(proc)
    try:
        rc = proc.wait(timeout=timeout_s or None)
        return 128 - rc if rc < 0 else rc
    except subprocess.TimeoutExpired:
        log.error("command timed out after %ss; killing process group",
                  timeout_s)
        kill_process_groups([proc.pid], grace_s=1.0)
        proc.wait()
        return 137
    finally:
        # The shell may have backgrounded children that survive its exit
        # (sh -c "serve.py &"); they share its group — reap them so no
        # user process outlives its supervisor. Free when the group is
        # already empty (first killpg raises ProcessLookupError).
        kill_process_groups([proc.pid], grace_s=0.5)


def poll_till_non_null(fn: Callable[[], Optional[object]],
                       interval_s: float = 3.0,
                       timeout_s: float = 0) -> Optional[object]:
    """Reference ``Utils.pollTillNonNull`` :91-145 — the executor's
    registration barrier poll."""
    deadline = time.monotonic() + timeout_s if timeout_s else None
    while True:
        result = fn()
        if result is not None:
            return result
        if deadline and time.monotonic() > deadline:
            return None
        time.sleep(interval_s)
