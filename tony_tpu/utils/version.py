"""Build/version stamping.

Reference: ``util/VersionInfo.java`` (149 LoC) injects build
version/revision/branch into the job configuration at submit time
(``TonyClient.java:152``), so the frozen artifact records exactly which
build ran the job. Here the same triple is resolved at submit from the
package version plus best-effort git metadata and stamped into the frozen
``tony-final.json`` under ``tony.internal.{version,revision,branch}``.
"""

from __future__ import annotations

import functools
import os
import subprocess
from typing import Dict


@functools.lru_cache(maxsize=1)
def version_info() -> Dict[str, str]:
    from tony_tpu import __version__

    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    def _git(*args: str) -> str:
        try:
            out = subprocess.run(
                ["git", *args], cwd=root, capture_output=True, text=True,
                timeout=5)
            return out.stdout.strip() or "unknown"
        except Exception:  # noqa: BLE001 — no git / not a checkout
            return "unknown"

    # Only stamp git metadata when the checkout is actually OURS: an
    # installed package under someone else's repo (site-packages inside a
    # project checkout) would otherwise record the USER's revision as the
    # framework build — wrong provenance is worse than "unknown".
    toplevel = _git("rev-parse", "--show-toplevel")
    ours = toplevel != "unknown" and \
        os.path.realpath(toplevel) == os.path.realpath(root)
    return {
        "version": __version__,
        "revision": _git("rev-parse", "--short", "HEAD") if ours
        else "unknown",
        "branch": _git("rev-parse", "--abbrev-ref", "HEAD") if ours
        else "unknown",
    }
