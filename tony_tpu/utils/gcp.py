"""Shared GCP auth: OAuth2 bearer resolution for stdlib-HTTP clients.

One resolution order for every GCP-speaking component (the GCS storage
client, the Cloud TPU provisioner): explicit credential → the
``GOOGLE_OAUTH_ACCESS_TOKEN`` env var → the GCE/TPU-VM metadata server,
cached and refreshed 60 s before expiry, with a 5-minute negative cache
off-GCP (no metadata server → anonymous; paying the connect timeout per
request would turn an N-call anonymous workload into N stalls).

This is the TPU-native analogue of the reference's single delegation-token
fetch shared across its HDFS touchpoints (``security/TokenCache.java:44-51``
feeding both localization and history writes). Factored out of
``storage/store.py`` when the TPU provisioner became the second client.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional, Type
from urllib import error as urlerror
from urllib import request as urlrequest

METADATA_ROOT = "http://metadata.google.internal"
_TOKEN_PATH = ("/computeMetadata/v1/instance/service-accounts/default/token")


class GcpBearer:
    """Bearer-token provider with caching and a 401-invalidated refresh."""

    def __init__(self, credential: Optional[str] = None,
                 metadata_root: Optional[str] = None):
        self.explicit = credential
        self._token: Optional[str] = credential
        self._expiry = float("inf") if credential else 0.0
        self._anon_until = 0.0
        self._root = (metadata_root or METADATA_ROOT).rstrip("/")

    def token(self) -> Optional[str]:
        # Expiry deadlines live on the MONOTONIC clock: expires_in is a
        # relative duration, and an NTP step must not make a live token
        # look expired (or worse, a stale one look fresh).
        if self._token and time.monotonic() < self._expiry - 60:
            return self._token
        env_tok = os.environ.get("GOOGLE_OAUTH_ACCESS_TOKEN")
        if env_tok:
            self._token, self._expiry = env_tok, float("inf")
            return self._token
        if time.monotonic() < self._anon_until:
            return None
        try:
            req = urlrequest.Request(self._root + _TOKEN_PATH,
                                     headers={"Metadata-Flavor": "Google"})
            with urlrequest.urlopen(req, timeout=5) as r:
                body = json.loads(r.read().decode())
            self._token = body.get("access_token")
            self._expiry = time.monotonic() + float(
                body.get("expires_in", 300))
        except Exception:  # noqa: BLE001 — off-GCP: anonymous
            self._token = None
            self._anon_until = time.monotonic() + 300
        return self._token

    def invalidate(self) -> None:
        """Drop the cached token (a 401 on a stale env/metadata token);
        explicit credentials are the caller's problem and stay."""
        if self.explicit is None:
            self._token, self._expiry = None, 0.0


def json_request(method: str, url: str, auth: GcpBearer,
                 body: Optional[dict] = None, retries: int = 4,
                 backoff_s: float = 1.0, timeout_s: float = 60.0,
                 error_cls: Type[Exception] = RuntimeError) -> dict:
    """One JSON-API call with bearer auth and bounded retry — the retry
    discipline shared by GCP control-plane clients (the Cloud TPU
    provisioner today): 429/5xx/transport errors retry with exponential
    backoff, 404 raises FileNotFoundError, 401/403 gets ONE cached-token
    refresh then raises ``error_cls`` (long jobs must survive token expiry
    between their first and last API call), any other 4xx raises
    ``error_cls`` immediately. ``error_cls`` instances carry the HTTP
    status in ``.code`` when their constructor accepts a ``code`` kwarg.

    ``GcsStore._request`` (storage/store.py) keeps its own loop on
    purpose: the *object* plane needs 308/Range resumable handling,
    response headers, and streamed bodies that a JSON helper shouldn't
    grow.
    """
    def _raise(msg: str, code: int, cause: Exception):
        try:
            exc = error_cls(msg, code=code)  # type: ignore[call-arg]
        except TypeError:
            exc = error_cls(msg)
        raise exc from cause

    data = json.dumps(body).encode() if body is not None else None
    delay = backoff_s
    refreshed_auth = False
    attempt = 0
    while True:
        headers = {"Content-Type": "application/json"}
        tok = auth.token()
        if tok:
            headers["Authorization"] = f"Bearer {tok}"
        req = urlrequest.Request(url, data=data, headers=headers,
                                 method=method)
        try:
            with urlrequest.urlopen(req, timeout=timeout_s) as r:
                return json.loads(r.read().decode() or "{}")
        except urlerror.HTTPError as e:
            detail = e.read().decode(errors="replace")[:512]
            if e.code == 404:
                raise FileNotFoundError(f"{method} {url}: not found") from e
            if e.code in (401, 403):
                if not refreshed_auth and auth.explicit is None:
                    refreshed_auth = True
                    auth.invalidate()
                    continue
                _raise(f"API denied {method} {url}: HTTP {e.code} "
                       f"({detail})", e.code, e)
            if e.code not in (408, 429) and e.code < 500:
                # 409 conflict, 400 bad request, … — the caller's
                # problem, not a retry candidate.
                _raise(f"API {method} {url}: HTTP {e.code} ({detail})",
                       e.code, e)
            last: Exception = e
        except (urlerror.URLError, OSError) as e:
            last = e
        if attempt >= retries:
            try:
                exc = error_cls(f"API {method} {url} failed after "
                                f"{retries + 1} attempts: {last}")
            except TypeError:
                exc = error_cls(str(last))
            raise exc from last
        attempt += 1
        time.sleep(delay)
        delay *= 2
