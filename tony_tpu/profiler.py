"""User-side profiler capture: chief-only XLA trace windows into the job dir.

The reference's observability is TensorBoard-only (chief reserves TB_PORT,
url registered to the AM, ``TaskExecutor.java:311-319``); SURVEY.md §5
calls for the TPU-native half: actual profiler traces (XLA/TPU timeline,
viewable in TensorBoard's profile plugin or Perfetto) collected into the
job's history dir and surfaced by the portal.

Contract: when ``tony.application.profiler-enabled`` is set, the
coordinator exports ``TONY_PROFILE_DIR`` to the CHIEF task only (one trace
per job, from the process that sees the whole step). User code wraps the
steps it wants captured:

    from tony_tpu import profiler
    with profiler.trace_window():
        state, loss = train_step(state, batch)

Everything no-ops when the env is absent, so the same training script runs
unchanged with profiling on or off — the same design as the reference's
TB_PORT contract (set for chief, ignored elsewhere).
"""

from __future__ import annotations

import contextlib
import logging
import os
from typing import Iterator, Optional

PROFILE_DIR_ENV = "TONY_PROFILE_DIR"

log = logging.getLogger(__name__)


def profile_dir() -> Optional[str]:
    """The trace destination, or None when this task shouldn't profile."""
    return os.environ.get(PROFILE_DIR_ENV) or None


@contextlib.contextmanager
def trace_window(name: str = "trace") -> Iterator[Optional[str]]:
    """Capture a jax profiler trace of the enclosed block into
    ``$TONY_PROFILE_DIR/<name>``; no-op (yields None) when unset."""
    dest = profile_dir()
    if not dest:
        yield None
        return
    import jax

    out = os.path.join(dest, name)
    os.makedirs(out, exist_ok=True)
    jax.profiler.start_trace(out)
    try:
        yield out
    finally:
        jax.profiler.stop_trace()
        log.info("profiler trace written to %s", out)
