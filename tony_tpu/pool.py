"""Warm executor pool: pre-spawned executors a submit adopts instead of
cold-spawning.

TonY paid the cold-start tax on every job — container allocation plus
HDFS localization before a single user process ran (SURVEY §1 L4). The
span-profiled cold path here shows the same shape: most of the
submit→first-step budget is interpreter boot + imports + backend init in
processes that are identical across jobs. Maple (PAPERS.md) decouples job
arrival from resource acquisition; Arax decouples jobs from the
accelerators they land on. This module is that move for executors: a
daemon keeps N **warm workers** alive — Python up, ``tony_tpu`` (and
optionally jax) imported, the persistent compile cache mounted — and a
``pool.lease`` RPC hands one to a backend at launch time.

Roles:

- **warm worker** (``python -m tony_tpu.pool worker --dir D``): preloads,
  writes ``ready.json``, then polls its directory for ``lease.json``. On
  a lease it applies the task env, chdirs into the task workdir,
  redirects stdio to the task logs, and runs the ordinary
  ``TaskExecutor`` — from the coordinator's side an adopted executor is
  indistinguishable from a cold-spawned one (same registration, same
  generation fencing, same heartbeats). At exit it writes
  ``pool-exit.json`` into the task workdir (the backend's completion
  source — the process is the daemon's child, not the backend's) and
  dies. **One lease per worker, ever**: a used (or crashed, or merely
  dirty) worker is never returned to the pool; the daemon replenishes
  with a fresh spawn.
- **daemon** (``python -m tony_tpu.pool serve --dir D --size N``): spawns
  and replenishes workers, serves ``pool.lease`` / ``pool.discard`` /
  ``pool.status`` / ``pool.stop`` over the ordinary RPC plane
  (rpc/wire.py, token-authenticated), and enforces hygiene: workers
  older than ``--max-lease-age-s`` are recycled, and leases carry the
  coordinator generation so a stale epoch's lease attempt is refused
  (``tony.pool.*`` conf keys; ``tony-tpu pool start/stop/status`` CLI).
- **backend adoption** (cluster/local.py): with ``tony.pool.dir`` set,
  ``launch_task`` tries a lease first and falls back to the cold spawn on
  ANY pool failure — refused lease, dead-on-adoption, stale generation,
  daemon gone (fault sites ``pool.lease`` / ``pool.adopt`` /
  ``pool.stale`` rehearse each shape deterministically). Pool trouble can
  slow a submit back to cold-start speed; it can never fail a job.

This is the LocalSim-backed seam the future cluster daemon (ROADMAP item
1) plugs into: the same lease contract, served per-host by the daemon
that also owns slice leases.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import subprocess
import sys
import threading
import time
import uuid
from typing import Dict, List, Optional, Tuple

from tony_tpu import constants
from tony_tpu.devtools.race import guarded

log = logging.getLogger(__name__)

#: worker-dir protocol files (all JSON, atomically replaced)
READY_FILE = "ready.json"        # worker → daemon: warm and leasable
LEASE_FILE = "lease.json"        # daemon → worker: adopt this task
ADOPTED_FILE = "adopted.json"    # worker → daemon: env applied, running
SHUTDOWN_FILE = "shutdown"       # daemon → worker: exit quietly

#: how often a warm worker polls for its lease — the adoption latency
#: floor (50 ms keeps a warm resubmit well under the 2 s budget while
#: costing ~nothing idle).
_WORKER_POLL_S = 0.05


class PoolError(RuntimeError):
    """A lease could not be granted/honoured; callers fall back to the
    cold spawn path."""


def _atomic_json(path: str, obj: dict, mode: int = 0o644) -> None:
    """Durable JSON drop: these files are the daemon↔worker handoff
    protocol (lease grant, adoption ack, exit report) — a torn write
    adopted as a valid lease or exit report corrupts a real job, so they
    get the full atomic_write discipline, not just tmp+rename."""
    from tony_tpu.utils.durable import atomic_write

    atomic_write(path, json.dumps(obj).encode("utf-8"), mode=mode)


def _read_json(path: str) -> Optional[dict]:
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        return data if isinstance(data, dict) else None
    except (OSError, ValueError):
        return None


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


# ---------------------------------------------------------------------------
# Warm worker
# ---------------------------------------------------------------------------
def _preload(preload: str) -> List[str]:
    """Import the configured modules while idle — the whole point of being
    warm. ``jax`` additionally initializes the backend (device scan +
    plugin load, the multi-second part) so an adopted executor's own
    tooling — and, via the hot OS page cache, the user process's import
    of the same libraries — starts fast. Failures are logged and skipped:
    a pool on a CPU-only host must still warm the rest."""
    import importlib

    done: List[str] = []
    # The executor module itself is always preloaded: adopting means
    # running TaskExecutor, and its transitive imports (rpc, runtimes,
    # storage) are a measurable slice of the cold spawn.
    mods = ["tony_tpu.executor.executor", "tony_tpu.runtimes.frameworks"]
    mods += [m.strip() for m in (preload or "").split(",") if m.strip()]
    for mod in mods:
        try:
            m = importlib.import_module(mod)
            if mod == "jax":
                m.devices()          # backend init, not just import
            done.append(mod)
        except Exception as e:  # noqa: BLE001 — warm what we can
            log.warning("preload of %s failed: %s", mod, e)
    return done


def _worker_main(worker_dir: str, preload: str) -> int:
    """Entry point of one warm worker process."""
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s")
    started_ts = time.time()          # wall anchor for the record only
    t0 = time.monotonic()
    loaded = _preload(preload)
    _atomic_json(os.path.join(worker_dir, READY_FILE), {
        "pid": os.getpid(), "started_ts": started_ts,
        "warm_after_s": round(time.monotonic() - t0, 3),
        # Which physical host this worker warmed up on (the slice
        # backend exports it into the environment) — the lease path
        # refuses workers whose host the fleet health ledger cordoned.
        "host": os.environ.get(constants.HOST_ID_ENV, ""),
        "preloaded": loaded})
    lease_path = os.path.join(worker_dir, LEASE_FILE)
    shutdown_path = os.path.join(worker_dir, SHUTDOWN_FILE)
    while True:
        if os.path.exists(shutdown_path):
            return 0
        lease = _read_json(lease_path)
        if lease is not None:
            break
        time.sleep(_WORKER_POLL_S)

    env = {str(k): str(v) for k, v in (lease.get("env") or {}).items()}
    workdir = str(lease.get("workdir") or "")
    os.makedirs(workdir, exist_ok=True)
    os.chdir(workdir)
    # Same log placement as a cold-spawned executor (cluster/local.py):
    # the coordinator's log surfaces read the task dir, not the pool dir.
    out = os.open(os.path.join(workdir, "stdout.log"),
                  os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    err = os.open(os.path.join(workdir, "stderr.log"),
                  os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    sys.stdout.flush()
    sys.stderr.flush()
    os.dup2(out, 1)
    os.dup2(err, 2)
    os.close(out)
    os.close(err)
    os.environ.update(env)
    _atomic_json(os.path.join(worker_dir, ADOPTED_FILE), {
        "pid": os.getpid(), "task_id": env.get(constants.TASK_ID, ""),
        "adopted_ts": time.time()})
    # From here the process IS a task executor: same fault arming, same
    # signal forwarding, same run loop as `python -m tony_tpu.executor`.
    from tony_tpu import faults
    from tony_tpu.executor.executor import TaskExecutor, _forward_signal

    faults.install_from_env()
    signal.signal(signal.SIGTERM, _forward_signal)
    signal.signal(signal.SIGINT, _forward_signal)
    try:
        code = TaskExecutor().run()
    except SystemExit as e:
        code = int(e.code or 0)
    except BaseException:  # noqa: BLE001
        log.exception("adopted executor crashed")
        code = constants.EXIT_FAILURE
    _atomic_json(os.path.join(workdir, constants.POOL_EXIT_FILE),
                 {"exit_code": int(code), "pid": os.getpid()})
    return int(code)


# ---------------------------------------------------------------------------
# Daemon
# ---------------------------------------------------------------------------
class _Worker:
    def __init__(self, worker_id: str, wdir: str, popen: subprocess.Popen):
        self.id = worker_id
        self.dir = wdir
        self.popen = popen
        self.created = time.monotonic()
        self.leased_to: str = ""       # task_id once leased
        self.lease_app: str = ""

    def ready(self) -> Optional[dict]:
        if self.leased_to or self.popen.poll() is not None:
            return None
        return _read_json(os.path.join(self.dir, READY_FILE))


class _PoolService:
    """RPC surface (rpc/wire.py namespacing: ``pool.lease`` etc.)."""

    def __init__(self, daemon: "PoolDaemon"):
        self._d = daemon

    def pool__lease(self, task_id: str, env: dict, workdir: str,
                    app_id: str = "", generation: int = 0) -> dict:
        return self._d.lease(task_id, env or {}, workdir,
                             app_id=app_id, generation=int(generation or 0))

    def pool__discard(self, worker_id: str, reason: str = "") -> bool:
        return self._d.discard(worker_id, reason)

    def pool__status(self) -> dict:
        return self._d.status()

    def pool__stop(self) -> bool:
        self._d.request_stop()
        return True


@guarded
class PoolDaemon:
    #: tonyrace registry (devtools/race.py): the worker map and the
    #: per-app generation fence are shared between the replenish loop
    #: and pool.lease/discard/status RPC threads — every touch holds
    #: the daemon lock.
    GUARDED_BY = {
        "_workers": "_lock",
        "_gen_by_app": "_lock",
    }

    def __init__(self, pool_dir: str, size: int = 2, preload: str = "jax",
                 max_lease_age_s: float = 600.0,
                 python: str = sys.executable,
                 jax_cache_dir: str = ""):
        self.pool_dir = os.path.abspath(pool_dir)
        self.size = max(1, int(size))
        self.preload = preload
        self.max_lease_age_s = float(max_lease_age_s)
        self.python = python
        self.jax_cache_dir = jax_cache_dir
        self._workers: Dict[str, _Worker] = {}
        self._lock = threading.Lock()
        self._stop_evt = threading.Event()
        # Highest coordinator generation seen per app: a lease carrying a
        # LOWER generation comes from a zombie epoch (superseded
        # coordinator still launching) and is refused — the same fencing
        # discipline as the RPC plane (rpc/wire.py).
        self._gen_by_app: Dict[str, int] = {}
        import secrets

        self.token = secrets.token_hex(16)
        from tony_tpu.rpc.wire import RpcServer

        self.rpc = RpcServer(_PoolService(self), host="127.0.0.1", port=0,
                             token=self.token)

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        os.makedirs(os.path.join(self.pool_dir, "workers"), exist_ok=True)
        self._replenish()
        self.rpc.start()
        host, port = self.rpc.address
        addr_path = os.path.join(self.pool_dir, constants.POOL_ADDR_FILE)
        # 0600 from the first byte — the file carries the RPC token
        # (same discipline as the coordinator address file).
        _atomic_json(addr_path,
                     {"host": host, "port": port, "token": self.token,
                      "pid": os.getpid(), "size": self.size}, mode=0o600)
        log.info("pool daemon up at %s:%d (%d warm executors, preload=%r)",
                 host, port, self.size, self.preload)

    def run(self) -> int:
        """Serve until pool.stop/SIGTERM; replenish as leases consume
        workers."""
        self.start()
        try:
            while not self._stop_evt.wait(0.2):
                self._replenish()
        finally:
            self._shutdown()
        return 0

    def request_stop(self) -> None:
        self._stop_evt.set()

    def _shutdown(self) -> None:
        with self._lock:
            workers = list(self._workers.values())
        for w in workers:
            if w.leased_to:
                # A leased executor belongs to a running job; killing it
                # here would fail that job from the janitor's chair.
                log.warning("pool stop: leaving leased worker %s "
                            "(task %s) to its coordinator", w.id,
                            w.leased_to)
                continue
            self._kill_worker(w)
        try:
            os.unlink(os.path.join(self.pool_dir,
                                   constants.POOL_ADDR_FILE))
        except OSError:
            pass
        self.rpc.stop()

    def _kill_worker(self, w: _Worker) -> None:
        try:
            with open(os.path.join(w.dir, SHUTDOWN_FILE), "w"):
                pass
        except OSError:
            pass
        if w.popen.poll() is None:
            try:
                os.killpg(w.popen.pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
        with self._lock:
            self._workers.pop(w.id, None)

    # -- worker fleet ----------------------------------------------------
    def _spawn_worker(self) -> None:
        worker_id = uuid.uuid4().hex[:8]
        wdir = os.path.join(self.pool_dir, "workers", worker_id)
        os.makedirs(wdir, exist_ok=True)
        env = dict(os.environ)
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        env["PYTHONPATH"] = (repo_root + os.pathsep +
                             env.get("PYTHONPATH", "")).rstrip(os.pathsep)
        if self.jax_cache_dir:
            # Mount the persistent compile cache for the warm backend
            # init AND for the user processes the adopted executor will
            # spawn (they inherit the executor env).
            env.setdefault(constants.JAX_COMPILATION_CACHE_DIR,
                           os.path.expanduser(self.jax_cache_dir))
        wlog = open(os.path.join(wdir, "worker.log"), "ab")
        popen = subprocess.Popen(
            [self.python, "-m", "tony_tpu.pool", "worker",
             "--dir", wdir, "--preload", self.preload],
            stdout=wlog, stderr=subprocess.STDOUT, env=env,
            start_new_session=True)
        wlog.close()
        with self._lock:
            self._workers[worker_id] = _Worker(worker_id, wdir, popen)
        log.info("spawned warm worker %s (pid %d)", worker_id, popen.pid)

    def _replenish(self) -> None:
        """Keep `size` leasable workers: reap exited/leased-and-done
        records, recycle over-age warm workers (credential/env drift
        hygiene — tony.pool.max-lease-age-s), spawn the deficit."""
        now = time.monotonic()
        stale: List[_Worker] = []
        with self._lock:
            for w in list(self._workers.values()):
                if w.popen.poll() is not None:
                    # Worker exited: either its lease completed (the task
                    # is done) or it died warming up. Either way the
                    # record is garbage — leases never return to the pool.
                    self._workers.pop(w.id)
                    continue
                if not w.leased_to and now - w.created > self.max_lease_age_s:
                    stale.append(w)
            deficit = self.size - sum(
                1 for w in self._workers.values()
                if not w.leased_to and w.popen.poll() is None)
        for w in stale:
            log.info("recycling over-age warm worker %s (%.0fs > %.0fs)",
                     w.id, now - w.created, self.max_lease_age_s)
            self._kill_worker(w)
            deficit += 0  # replacement accounted by next pass
        for _ in range(max(0, deficit)):
            self._spawn_worker()

    def _cordoned_hosts(self) -> Dict[str, str]:
        """The fleet daemon's health-cordon handshake: it atomically
        replaces health.cordon.json in this pool dir on every export
        (fleet/health.py write_cordon_file). Absent/garbled = no fleet
        or health off — nothing cordoned."""
        from tony_tpu.fleet.health import read_cordoned

        return read_cordoned(os.path.join(self.pool_dir,
                                          constants.FLEET_CORDON_FILE))

    # -- RPC behaviour ---------------------------------------------------
    def lease(self, task_id: str, env: dict, workdir: str,
              app_id: str = "", generation: int = 0) -> dict:
        """Grant one warm worker to a task, or raise PoolError (the caller
        cold-spawns). The worker is marked leased BEFORE the lease file
        lands, so two concurrent submits can never adopt the same pid.
        Workers warmed on a health-cordoned host are never leased — and
        are discarded on sight (a warm import cache on bad hardware is
        worth less than the retry it would burn)."""
        now = time.monotonic()
        cordoned = self._cordoned_hosts()
        sick: List[Tuple[_Worker, str]] = []
        with self._lock:
            if generation and app_id:
                last = self._gen_by_app.get(app_id, 0)
                if generation < last:
                    raise PoolError(
                        f"stale-generation lease for {app_id}: generation "
                        f"{generation} < observed {last}")
                self._gen_by_app[app_id] = generation
            candidate: Optional[_Worker] = None
            for w in self._workers.values():
                if w.leased_to or w.popen.poll() is not None:
                    continue
                if now - w.created > self.max_lease_age_s:
                    continue          # recycled by the next replenish pass
                ready = w.ready()
                if ready is None:
                    continue          # still warming up
                if cordoned and ready.get("host") in cordoned:
                    sick.append((w, str(ready.get("host"))))
                    continue
                candidate = w
                break
            if candidate is not None:
                candidate.leased_to = task_id
                candidate.lease_app = app_id
        for w, host in sick:
            log.warning("discarding warm worker %s: its host %s is "
                        "health-cordoned", w.id, host)
            self._kill_worker(w)
        if candidate is None:
            if sick:
                raise PoolError(
                    "pool has no warm executor available (workers on "
                    "health-cordoned hosts discarded: "
                    + ", ".join(sorted(h for _, h in sick)) + ")")
            raise PoolError("pool has no warm executor available")
        lease_env = dict(env)
        lease_env[constants.POOL_WORKER_ID] = candidate.id
        _atomic_json(os.path.join(candidate.dir, LEASE_FILE),
                     {"env": lease_env, "workdir": workdir,
                      "task_id": task_id})
        # Adoption ack: the worker applied the env and is running the
        # executor. A worker that dies between the grant and the ack is a
        # dead-on-adoption lease — surfaced here, not as a job failure.
        deadline = time.monotonic() + 5.0
        adopted_path = os.path.join(candidate.dir, ADOPTED_FILE)
        while time.monotonic() < deadline:
            if os.path.exists(adopted_path):
                break
            if candidate.popen.poll() is not None:
                with self._lock:
                    self._workers.pop(candidate.id, None)
                raise PoolError(
                    f"leased executor {candidate.id} died on adoption "
                    f"(exit {candidate.popen.returncode})")
            time.sleep(0.02)
        else:
            self._kill_worker(candidate)
            raise PoolError(
                f"leased executor {candidate.id} never acknowledged "
                f"adoption")
        log.info("leased worker %s (pid %d) to %s [%s gen %d]",
                 candidate.id, candidate.popen.pid, task_id, app_id,
                 generation)
        return {"worker_id": candidate.id, "pid": candidate.popen.pid,
                "age_s": round(now - candidate.created, 3)}

    def discard(self, worker_id: str, reason: str = "") -> bool:
        """A caller observed the leased worker dead/dirty: drop and
        replace it — a discarded lease is NEVER reused."""
        with self._lock:
            w = self._workers.get(worker_id)
        if w is None:
            return False
        log.warning("discarding worker %s (%s)", worker_id,
                    reason or "caller discard")
        self._kill_worker(w)
        return True

    def status(self) -> dict:
        now = time.monotonic()
        rows = []
        ready = leased = 0
        with self._lock:
            workers = list(self._workers.values())
        for w in workers:
            info = w.ready()
            state = ("leased" if w.leased_to
                     else "ready" if info is not None
                     else "dead" if w.popen.poll() is not None
                     else "warming")
            ready += state == "ready"
            leased += state == "leased"
            rows.append({"worker": w.id, "pid": w.popen.pid,
                         "state": state,
                         "age_s": round(now - w.created, 1),
                         "task": w.leased_to,
                         "preloaded": (info or {}).get("preloaded", [])})
        return {"pool_dir": self.pool_dir, "size": self.size,
                "ready": ready, "leased": leased, "workers": rows}


# ---------------------------------------------------------------------------
# Client helper (backends + CLI)
# ---------------------------------------------------------------------------
class PoolClient:
    """Thin lease client over the pool address file. Deliberately
    short-fused: the pool is an optimization, so a dead/absent daemon must
    cost milliseconds, not retry budgets — callers treat any failure as
    'cold spawn instead'."""

    def __init__(self, pool_dir: str):
        self.pool_dir = os.path.abspath(os.path.expanduser(pool_dir))
        self._rpc = None

    def _client(self):
        if self._rpc is None:
            addr = _read_json(os.path.join(self.pool_dir,
                                           constants.POOL_ADDR_FILE))
            if not addr:
                raise PoolError(f"no pool running under {self.pool_dir}")
            from tony_tpu.rpc.wire import RpcClient

            self._rpc = RpcClient(addr["host"], int(addr["port"]),
                                  token=addr.get("token") or None,
                                  max_retries=1, retry_sleep_s=0.1,
                                  connect_timeout_s=2.0,
                                  call_timeout_s=10.0,
                                  peer="pool")
        return self._rpc

    def call(self, method: str, **args):
        try:
            return self._client().call(method, **args)
        except PoolError:
            raise
        except Exception as e:  # noqa: BLE001 — normalize transport errors
            self.close()
            raise PoolError(f"pool rpc {method} failed: {e}") from e

    def lease(self, task_id: str, env: Dict[str, str], workdir: str,
              app_id: str = "", generation: int = 0) -> dict:
        res = self.call("pool.lease", task_id=task_id, env=dict(env),
                        workdir=workdir, app_id=app_id,
                        generation=generation)
        if not isinstance(res, dict) or "pid" not in res:
            raise PoolError(f"malformed lease response: {res!r}")
        return res

    def discard(self, worker_id: str, reason: str = "") -> None:
        try:
            self.call("pool.discard", worker_id=worker_id, reason=reason)
        except PoolError:
            pass                      # best-effort: daemon may be gone

    def close(self) -> None:
        if self._rpc is not None:
            try:
                self._rpc.close()
            except Exception:  # noqa: BLE001
                pass
            self._rpc = None


# ---------------------------------------------------------------------------
# Entrypoint
# ---------------------------------------------------------------------------
def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tony-tpu-pool")
    sub = p.add_subparsers(dest="role", required=True)
    s = sub.add_parser("serve", help="run the pool daemon (foreground)")
    s.add_argument("--dir", required=True)
    s.add_argument("--size", type=int, default=2)
    s.add_argument("--preload", default="jax")
    s.add_argument("--max-lease-age-s", type=float, default=600.0)
    s.add_argument("--jax-cache-dir", default="")
    w = sub.add_parser("worker", help="run one warm worker (internal)")
    w.add_argument("--dir", required=True)
    w.add_argument("--preload", default="jax")
    args = p.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s")
    if args.role == "worker":
        return _worker_main(args.dir, args.preload)
    daemon = PoolDaemon(args.dir, size=args.size, preload=args.preload,
                        max_lease_age_s=args.max_lease_age_s,
                        jax_cache_dir=args.jax_cache_dir)
    signal.signal(signal.SIGTERM, lambda *_: daemon.request_stop())
    signal.signal(signal.SIGINT, lambda *_: daemon.request_stop())
    return daemon.run()


if __name__ == "__main__":
    sys.exit(main())
