"""Pipeline parallelism: GPipe-style microbatched schedule over the ``pp``
mesh axis.

Nothing to cite in the reference — TonY has no tensor/pipeline/sequence
parallelism anywhere (SURVEY.md §2.3, verified absent); this is the genuinely
new TPU-first work the blueprint requires.

Design:
- All transformer blocks' params are **stacked on a leading "stage" axis**
  ``[n_layers, ...]`` sharded over ``pp`` (``DEFAULT_RULES`` maps
  ``stage → pp``). With ``n_layers % pp == 0``, jax.sharding hands each
  device a *contiguous* layer range — the classic stage assignment falls
  out of array sharding, no bespoke placement code.
- Inside ``shard_map`` each device scans its local ``[L/S, ...]`` params
  over its resident activation (``lax.scan`` — compiled once, not unrolled).
- The schedule is GPipe: split the local batch into M microbatches; at tick
  t, stage 0 injects microbatch t, every stage applies its layers to its
  resident activation, the last stage banks the finished microbatch
  ``t-(S-1)``, and activations rotate to the next stage via a single
  neighbour ``ppermute`` (pure ICI traffic; the ``pp`` axis is laid out so
  neighbours share links — mesh.py axis order). Total ticks ``M + S - 1``,
  bubble fraction ``(S-1)/(M+S-1)``.
- Embedding and the LM head run *outside* the shard_map, auto-sharded by
  jit like every other op. Composes with data parallelism: activations ride
  in sharded over ``(dp, fsdp)`` and stay that way inside (the shard_map
  covers those axes too, it just doesn't communicate over them).
- Backward is plain autodiff: ``ppermute``'s transpose is the reverse
  ppermute, so reverse-mode replays the schedule mirror-image — GPipe's
  backward pass without writing one. Per-layer ``jax.checkpoint`` keeps
  residency at O(activations · microbatch), not O(· full batch).

Why GPipe and not 1F1B — quantified, because the tradeoff is different on
TPU than in the papers:

- **The memory argument mostly disappears under remat.** 1F1B's benefit is
  capping in-flight microbatch stashes at S (stages) instead of M. With
  per-layer ``jax.checkpoint`` the stash per microbatch is only the stage
  boundary activation (``mb·seq·dim``), so the delta is
  ``(M−S)·mb·seq·dim·2 B`` — for the 8B flagship shape (mb=1, seq 8192,
  dim 4096, M=8, S=4) that is ~256 MB of 95 GB v5p HBM (<0.3%).
- **True 1F1B breaks SPMD uniformity where it counts.** Backward for
  microbatch t must start while t+1 is still in forward, which needs the
  last stage's lm_head+loss *inside* the tick loop. In a uniform SPMD
  program every stage would execute the head every tick (≈ +S× the head's
  ~10% FLOP share — +30% total at S=4); per-stage divergent programs are
  not expressible under one jit. GPipe's loop body is the same code on
  every stage every tick, and autodiff derives the mirror-image backward
  schedule from the ``ppermute`` transpose for free.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tony_tpu.compat import shard_map
from tony_tpu.models.transformer import (Block, TransformerConfig,
                                         causal_lm_loss)

from tony_tpu.parallel.mesh import BATCH_AXES

PP_AXIS = "pp"


def init_pipeline_params(cfg: TransformerConfig, rng: jax.Array
                         ) -> Dict[str, Any]:
    """Params pytree with every block stacked on a leading stage axis:
    ``{"embedding", "blocks"[n_layers, ...], "final_norm", "lm_head"}``."""
    r_blocks, r_emb, r_head = jax.random.split(rng, 3)
    dummy_x = jnp.zeros((1, 8, cfg.dim), cfg.dtype)
    dummy_pos = jnp.zeros((1, 8), jnp.int32)
    block = Block(cfg)

    def init_one(r):
        return nn.meta.unbox(block.init(r, dummy_x, dummy_pos))["params"]

    blocks = jax.vmap(init_one)(jax.random.split(r_blocks, cfg.n_layers))
    head_init = nn.initializers.lecun_normal()
    return {
        "embedding": (jax.random.normal(
            r_emb, (cfg.vocab_size, cfg.dim), cfg.param_dtype) * 0.02),
        "blocks": blocks,
        "final_norm": jnp.ones((cfg.dim,), cfg.param_dtype),
        "lm_head": head_init(r_head, (cfg.dim, cfg.vocab_size),
                             cfg.param_dtype),
    }


def _block_fsdp_axes(cfg: TransformerConfig) -> Dict[str, Any]:
    """Per-leaf index of the dimension to shard over ``fsdp`` in a block's
    params (the dim whose logical name maps to fsdp under DEFAULT_RULES —
    i.e. ``embed``), or None for leaves without one (norm scales). Indices
    are for the UNSTACKED leaf; the stacked stage axis goes in front."""
    from tony_tpu.parallel.sharding import DEFAULT_RULES

    rules = dict(DEFAULT_RULES)
    block = Block(cfg)
    dummy_x = jnp.zeros((1, 8, cfg.dim), cfg.dtype)
    dummy_pos = jnp.zeros((1, 8), jnp.int32)
    boxed = jax.eval_shape(block.init, jax.random.key(0), dummy_x,
                           dummy_pos)["params"]
    spec_tree = nn.get_partition_spec(boxed)

    def leaf_axis(spec):
        # -1 = no fsdp dim (None would vanish from the pytree structure)
        if not isinstance(spec, P):
            return -1
        for i, name in enumerate(spec):
            if name is not None and rules.get(name) == "fsdp":
                return i
        return -1

    return jax.tree.map(leaf_axis, spec_tree,
                        is_leaf=lambda x: isinstance(x, P) or x is None)


def _block_specs(fsdp_axes: Any, blocks: Any) -> Any:
    """PartitionSpecs for the stacked block leaves: stage axis over ``pp``
    plus each leaf's fsdp dim. Single source for BOTH the at-rest param
    shardings and the shard_map in_specs — if they diverged, shard_map
    would silently force a full reshard on entry."""
    def leaf_spec(ax, leaf):
        spec = [PP_AXIS] + [None] * (leaf.ndim - 1)
        if ax >= 0:
            spec[ax + 1] = "fsdp"
        return P(*spec)

    return jax.tree.map(leaf_spec, fsdp_axes, blocks)


def pipeline_param_shardings(mesh: Mesh, params: Dict[str, Any],
                             cfg: Optional[TransformerConfig] = None
                             ) -> Dict[str, Any]:
    """Composed shardings: stacked blocks over ``pp`` on the stage axis AND
    ``fsdp`` on each leaf's embed dim (gathered just-in-time inside the
    stage loop — see ``_stage_apply``); embedding/lm_head/final_norm —
    exactly the tensors that dominate memory at 8B scale — shard over
    fsdp/tp outside the shard_map. With fsdp>1, no leaf of the pipeline
    state is fully replicated."""
    if cfg is not None:
        spec_tree = _block_specs(_block_fsdp_axes(cfg), params["blocks"])
    else:   # stage-only sharding (no fsdp composition)
        spec_tree = jax.tree.map(lambda _: P(PP_AXIS), params["blocks"])
    # Embedding sharded on the VOCAB dim: an embed-sharded table makes the
    # lookup's output embed-sharded, which SPMD can only reshard to the
    # batch-sharded activations by full rematerialization (see
    # models/transformer.py embedding comment; XLA b/433785288).
    return {
        "embedding": NamedSharding(mesh, P("fsdp", None)),
        "blocks": jax.tree.map(lambda s: NamedSharding(mesh, s), spec_tree,
                               is_leaf=lambda x: isinstance(x, P)),
        "final_norm": NamedSharding(mesh, P("fsdp")),
        "lm_head": NamedSharding(mesh, P("fsdp", "tp")),
    }


def _stage_apply(cfg: TransformerConfig, fsdp_axes: Any, n_fsdp: int,
                 stage_params: Any, x: jax.Array,
                 positions: jax.Array) -> jax.Array:
    """Apply this device's contiguous layer range ([L/S, ...] stacked).

    With fsdp>1 the stage's params arrive as fsdp-local chunks; each
    layer's weights are all-gathered just-in-time inside the (possibly
    remat'd) apply — so the gather is recomputed in backward instead of
    living as a residual, and its transpose is the FSDP reduce-scatter of
    the gradients. This is FSDP-in-PP: at rest every block leaf is sharded
    over pp×fsdp."""
    block = Block(cfg)

    def apply_one(p_local, h):
        if n_fsdp > 1:
            p_local = jax.tree.map(
                lambda a, ax: a if ax < 0 else lax.all_gather(
                    a, "fsdp", axis=ax, tiled=True),
                p_local, fsdp_axes)
        return block.apply({"params": p_local}, h, positions)

    if cfg.remat:
        apply_one = jax.checkpoint(apply_one, prevent_cse=False)

    def body(h, layer_params):
        return apply_one(layer_params, h), None

    x, _ = lax.scan(body, x, stage_params)
    return x


def _pipeline_blocks(cfg: TransformerConfig, num_microbatches: int,
                     fsdp_axes: Any, n_fsdp: int,
                     blocks_local: Any, x: jax.Array,
                     positions: jax.Array) -> jax.Array:
    """Per-shard GPipe loop (runs inside shard_map over pp + batch axes).

    ``x``: [B_local, S, D] embedded activations (replicated over pp);
    ``blocks_local``: this stage's [L/S, ...] param stack.
    """
    n_stages = lax.psum(1, PP_AXIS)
    stage = lax.axis_index(PP_AXIS)
    m = num_microbatches
    b_loc, seq, d = x.shape
    mb = b_loc // m
    mbs = x.reshape(m, mb, seq, d)
    pos_mb = positions[:mb]

    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    state0 = jnp.zeros_like(mbs[0])
    out0 = jnp.zeros_like(mbs)

    def tick(carry, t):
        state, out = carry
        inject = lax.dynamic_index_in_dim(
            mbs, jnp.clip(t, 0, m - 1), axis=0, keepdims=False)
        state = jnp.where(stage == 0, inject, state)
        state = _stage_apply(cfg, fsdp_axes, n_fsdp, blocks_local, state,
                             pos_mb)
        done_idx = t - (n_stages - 1)
        banked = lax.dynamic_update_index_in_dim(
            out, state, jnp.clip(done_idx, 0, m - 1), axis=0)
        out = jnp.where((stage == n_stages - 1) & (done_idx >= 0),
                        banked, out)
        state = lax.ppermute(state, PP_AXIS, perm)
        return (state, out), None

    (_, out), _ = lax.scan(tick, (state0, out0),
                           jnp.arange(m + n_stages - 1))
    # Only the last stage holds non-zero outputs; psum replicates them over
    # pp so the head (outside the shard_map) sees a well-defined array.
    out = lax.psum(out, PP_AXIS)
    return out.reshape(b_loc, seq, d)


def pipeline_forward(cfg: TransformerConfig, mesh: Mesh,
                     params: Dict[str, Any], tokens: jax.Array,
                     num_microbatches: int = 2) -> jax.Array:
    """Causal-LM forward with the block stack pipelined over ``pp``.

    tokens [B, S] (B sharded over dp·fsdp; B/(dp·fsdp) must divide evenly
    into ``num_microbatches``) → logits [B, S, vocab] f32.
    """
    if cfg.n_layers % mesh.shape[PP_AXIS]:
        raise ValueError(
            f"n_layers={cfg.n_layers} not divisible by pp="
            f"{mesh.shape[PP_AXIS]}")
    if tokens.shape[1] > cfg.max_seq_len:
        raise ValueError(f"seq {tokens.shape[1]} > max {cfg.max_seq_len}")
    x = params["embedding"][tokens].astype(cfg.dtype)
    positions = jnp.broadcast_to(
        jnp.arange(tokens.shape[1], dtype=jnp.int32)[None, :], tokens.shape)

    n_fsdp = mesh.shape.get("fsdp", 1)
    if n_fsdp > 1:
        fsdp_axes = _block_fsdp_axes(cfg)
    else:
        fsdp_axes = jax.tree.map(lambda _: -1, params["blocks"])
    blocks_spec = _block_specs(fsdp_axes, params["blocks"])

    fn = functools.partial(_pipeline_blocks, cfg, num_microbatches,
                           fsdp_axes, n_fsdp)
    x = shard_map(
        fn, mesh=mesh,
        in_specs=(blocks_spec, P(BATCH_AXES), P(BATCH_AXES)),
        out_specs=P(BATCH_AXES), check_vma=False,
    )(params["blocks"], x, positions)

    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    xf = xf * lax.rsqrt(var + cfg.norm_eps) * params["final_norm"]
    return xf @ params["lm_head"].astype(jnp.float32)


def pipeline_loss(cfg: TransformerConfig, mesh: Mesh, params: Dict[str, Any],
                  tokens: jax.Array, num_microbatches: int = 2) -> jax.Array:
    logits = pipeline_forward(cfg, mesh, params, tokens, num_microbatches)
    return causal_lm_loss(logits, tokens)
