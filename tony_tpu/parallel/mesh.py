"""Device-mesh construction over TPU ICI/DCN topology.

The reference framework's unit of placement is the YARN container matched to a
task by priority (``TonySession.java:208``); tensors never cross its mind. Here
the unit of placement is a **mesh axis**: every parallelism strategy is a named
axis of a `jax.sharding.Mesh`, and XLA inserts the collectives (psum /
all_gather / reduce_scatter / ppermute) that ride ICI within a slice and DCN
across slices.

Axis order encodes the physical hierarchy (scaling-book recipe): the outermost
axes change slowest across the device array, so we put DCN-friendly,
low-traffic axes (``dp``, then ``pp``) outermost and bandwidth-hungry axes
(``tp``) innermost where neighbours share ICI links.

Axes:
    dp    pure data parallelism (gradient psum only — cheapest, DCN-safe)
    fsdp  data parallelism with sharded params/optimizer (all_gather weights)
    pp    pipeline stages (point-to-point ppermute between neighbours)
    ep    expert parallelism for MoE (all_to_all dispatch)
    sp    sequence/context parallelism (ring ppermute / all_to_all)
    tp    tensor parallelism (activation all_reduce every layer — ICI only)
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Outermost (slow, DCN-tolerant) → innermost (fast, wants ICI neighbours).
# ``dcn_dp`` is the multislice axis: pure data parallelism ACROSS slices,
# whose only collective (the gradient psum) is the one thing DCN bandwidth
# can afford — every other axis stays inside a slice on ICI. Size 1 on a
# single slice, so single-slice code never notices it.
MESH_AXES = ("dcn_dp", "dp", "fsdp", "pp", "ep", "sp", "tp")
# Every axis that consumes the batch dim — the single source of truth
# (rules, pipeline, data pipeline all import this).
BATCH_AXES = ("dcn_dp", "dp", "fsdp")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Sizes for each mesh axis. At most one axis may be -1 (inferred so the
    product equals the device count). Unused axes stay 1 — they are kept in
    the mesh so sharding rules are uniform across strategies."""

    dcn_dp: int = 1
    dp: int = -1
    fsdp: int = 1
    pp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    def sizes(self) -> Sequence[int]:
        return tuple(getattr(self, a) for a in MESH_AXES)

    def resolve(self, n_devices: int) -> "MeshSpec":
        sizes = list(self.sizes())
        bad = [s for s in sizes if s < 1 and s != -1]
        if bad:
            raise ValueError(
                f"axis sizes must be positive or -1 (inferred), got {self}")
        unknown = [i for i, s in enumerate(sizes) if s == -1]
        if len(unknown) > 1:
            raise ValueError(f"at most one axis may be -1, got {self}")
        known = math.prod(s for s in sizes if s != -1)
        if unknown:
            if n_devices % known:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes "
                    f"product {known} in {self}")
            sizes[unknown[0]] = n_devices // known
        elif known != n_devices:
            raise ValueError(
                f"mesh {self} wants {known} devices, have {n_devices}")
        return MeshSpec(**dict(zip(MESH_AXES, sizes)))

    def respec(self, n_devices: int) -> "MeshSpec":
        """Re-solve this spec for a NEW device count — the elastic
        shrink/grow recipe (coordinator/elastic.py): the model axes
        (fsdp/pp/ep/sp/tp) keep their shapes so saved shards stay
        compatible, and the pure-data axis ``dp`` absorbs the delta.
        Raises when the fixed axes don't divide the new count (shrink
        below the model-parallel footprint needs a different spec)."""
        d = dict(zip(MESH_AXES, self.sizes()))
        d["dp"] = -1
        return MeshSpec(**d).resolve(n_devices)

    @classmethod
    def from_string(cls, s: str) -> "MeshSpec":
        """Parse ``"dp=2,tp=4"`` — the config-file form
        (key ``tony.tpu.mesh-shape``, see ``conf/keys.py``)."""
        kwargs = {}
        for part in filter(None, (p.strip() for p in s.split(","))):
            k, sep, v = part.partition("=")
            if k not in MESH_AXES:
                raise ValueError(f"unknown mesh axis {k!r} (not in "
                                 f"{MESH_AXES})")
            if not sep or not v.lstrip("-").isdigit():
                raise ValueError(
                    f"expected axis=size in {part!r} (e.g. 'tp=4')")
            kwargs[k] = int(v)
        if "dp" not in kwargs:
            kwargs["dp"] = -1
        return cls(**kwargs)


def build_mesh(spec: Optional[MeshSpec] = None,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a Mesh whose axis layout respects physical topology.

    On real TPU slices `mesh_utils.create_device_mesh` maps axes onto the
    torus so innermost axes land on ICI neighbours; on a host-platform
    (CPU test) mesh the devices are virtual and a plain reshape suffices.
    """
    devices = list(devices if devices is not None else jax.devices())
    spec = (spec or MeshSpec()).resolve(len(devices))
    sizes = spec.sizes()
    if devices[0].platform == "tpu":
        from jax.experimental import mesh_utils
        if spec.dcn_dp > 1:
            # Multislice: per-slice axes laid out on each slice's torus,
            # dcn_dp across slices (grouped by device.slice_index).
            dev_array = mesh_utils.create_hybrid_device_mesh(
                (1,) + tuple(sizes[1:]),
                (spec.dcn_dp,) + (1,) * (len(sizes) - 1),
                devices=devices)
        else:
            dev_array = mesh_utils.create_device_mesh(sizes,
                                                      devices=devices)
    else:
        # Virtual/CPU: contiguous groups stand in for slices.
        dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, MESH_AXES)


@functools.lru_cache(maxsize=256)
def _cached_batch_sharding(mesh: Mesh, extra_dims: int) -> NamedSharding:
    return NamedSharding(mesh, P(BATCH_AXES, *([None] * extra_dims)))


def batch_sharding(mesh: Mesh, extra_dims: int = 1) -> NamedSharding:
    """Sharding for a [batch, ...] input: batch split over every
    data-parallel-ish axis (dcn_dp, dp and fsdp all consume batch).
    Memoized per (mesh, extra_dims): large batch pytrees map every leaf
    through here on the submit path, and NamedSharding construction is
    not free — identical requests return the same object."""
    return _cached_batch_sharding(mesh, extra_dims)


def tree_batch_shardings(mesh: Mesh, sample_batch: Any) -> Any:
    """Per-leaf batch shardings for a whole batch pytree: [batch, ...]
    leaves split over the batch axes, scalar (0-d) leaves replicated —
    the one shared recipe for ``jit_train_step`` and the grad-sync accum
    step. Shardings are memoized per (mesh, ndim), so a batch tree with
    thousands of leaves pays for at most a handful of constructions."""
    import jax.numpy as jnp

    replicated = replicated_sharding(mesh)
    return jax.tree.map(
        lambda leaf: (batch_sharding(mesh, extra_dims=jnp.ndim(leaf) - 1)
                      if jnp.ndim(leaf) > 0 else replicated),
        sample_batch)


@functools.lru_cache(maxsize=256)
def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
