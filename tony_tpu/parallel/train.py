"""Sharded training-state construction and jit'd train steps.

Everything here compiles to ONE XLA program per step: forward, backward,
optimizer update, and every collective (gradient psum over dp/fsdp, weight
all_gathers for FSDP, activation all_reduces for TP) — traced once, fused by
XLA, no Python in the hot loop. This replaces the reference's entire "data
plane is someone else's problem" stance (SURVEY.md §2.4).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tony_tpu import compat
from tony_tpu.parallel.mesh import tree_batch_shardings
from tony_tpu.parallel.sharding import DEFAULT_RULES, param_shardings


@struct.dataclass
class TrainState:
    """Minimal train state (flax train_state analogue, kept dependency-light
    so checkpointing sees a plain pytree)."""
    step: jax.Array
    params: Any
    opt_state: Any
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    def apply_gradients(self, grads: Any) -> "TrainState":
        updates, new_opt = self.tx.update(grads, self.opt_state, self.params)
        return self.replace(step=self.step + 1,
                            params=optax.apply_updates(self.params, updates),
                            opt_state=new_opt)


def init_sharded_state(
    model: nn.Module,
    sample_batch: Any,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    rng: Optional[jax.Array] = None,
    rules: Sequence[Tuple[str, Any]] = DEFAULT_RULES,
) -> Tuple[TrainState, TrainState]:
    """Initialize params *already sharded*: eval_shape under logical rules →
    compute NamedShardings → jit init with out_shardings so no device ever
    materializes the full model (essential at 8B+ params).

    Returns ``(state, state_shardings)``; the latter mirrors the state tree
    with a NamedSharding at every leaf (optimizer-slot shardings come from
    XLA's sharding propagation through ``tx.init`` on sharded params).
    """
    rng = rng if rng is not None else jax.random.key(0)

    def boxed_init(rng):
        # Params stay wrapped in LogicallyPartitioned metadata boxes here, so
        # tx.init's tree_maps produce *boxed optimizer slots* too — the slots
        # inherit each param's logical axes and therefore its sharding.
        params = model.init(rng, sample_batch)["params"]
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=tx.init(params), tx=tx)

    with nn.logical_axis_rules(list(rules)):
        abstract = jax.eval_shape(boxed_init, rng)
    state_sh = param_shardings(mesh, abstract, rules)

    def init_fn(rng):
        return nn.meta.unbox(boxed_init(rng))

    with compat.set_mesh(mesh), nn.logical_axis_rules(list(rules)):
        state = jax.jit(init_fn, out_shardings=state_sh)(rng)
    return state, state_sh


def jit_train_step(
    loss_fn: Callable[[Any, Any, jax.Array], Tuple[jax.Array, dict]],
    mesh: Mesh,
    state_shardings: TrainState,
    sample_batch: Any,
    rules: Sequence[Tuple[str, Any]] = DEFAULT_RULES,
    donate: bool = True,
):
    """Build the canonical step function. ``loss_fn(params, batch, rng)``
    must be pure/jit-safe and return ``(loss, aux_metrics)``.

    Returns ``step(state, batch, rng) -> (state, metrics)`` compiled with
    explicit in/out shardings: batch sharded over (dp, fsdp) on dim 0, state
    per ``state_shardings`` — XLA derives every collective from there.
    """
    def step(state: TrainState, batch: Any, rng: jax.Array):
        with nn.logical_axis_rules(list(rules)):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state.params, batch, rng)
        new_state = state.apply_gradients(grads)
        metrics = {"loss": loss, "step": new_state.step, **aux}
        return new_state, metrics

    # Scalar (0-d) leaves can't carry a batch dim — replicate those.
    # Shardings are memoized per (mesh, ndim) in mesh.py, so a large
    # batch pytree no longer pays one NamedSharding construction per
    # leaf per builder call on the submit path.
    batch_sh = tree_batch_shardings(mesh, sample_batch)
    jitted = jax.jit(
        step,
        in_shardings=(state_shardings, batch_sh, NamedSharding(mesh, P())),
        out_shardings=(state_shardings, NamedSharding(mesh, P())),
        donate_argnums=(0,) if donate else ())

    def wrapped(state, batch, rng):
        with compat.set_mesh(mesh):
            return jitted(state, batch, rng)

    return wrapped
