"""Overlapped, bucketed cross-slice gradient synchronization.

``jit_train_step`` (parallel/train.py) compiles forward, backward and the
gradient reduction into ONE XLA program — correct, but the cross-slice
(``dcn_dp``) all-reduce then materializes as a single monolithic psum that
XLA schedules strictly behind the whole backward pass, and nothing on the
host can attribute the time it takes: the DCN wait books as
``step_compute`` and a COMMS_BOUND job looks healthy (the exact blind spot
docs/operations.md called out). This module replaces that monolith with
the structure DDP-style systems use:

1. **Microbatched accumulation** (``tony.train.accum-steps``): the global
   batch is split into A microbatches scanned inside one program; grads
   accumulate locally, so the cross-slice sync runs once per A backward
   passes — the compute:DCN ratio rises A-fold.
2. **Per-slice gradients, explicitly.** Instead of letting XLA insert the
   batch-axis reduction, the accumulate program computes grads *per sync
   slice* (``jax.vmap`` over a leading slice dim sharded over the sync
   axes) and returns them UNSYNCED — the cross-slice reduction has not
   happened yet when the program ends.
3. **Bucketed, order-stable sync** (``tony.train.bucket-mb``): the sync
   program flattens the stacked grads in tree order, packs them into
   ≤bucket-MiB buckets (a param bigger than the bucket spills into its
   own), and mean-reduces each bucket over the slice dim — one
   independent all-reduce per bucket that XLA's async collectives can
   overlap, instead of one serialized monolith. Packing order is the
   tree-flatten order both here and in the split-back, so the result is
   deterministic and allclose to the monolithic psum.
4. **An attributable comms phase.** Because the sync is its own dispatch,
   the host wraps it in ``telemetry.phase("comms")`` anchored with
   ``block_until_ready`` — the dcn_dp MULTICHIP dryrun and any
   instrumented job finally report a real comms fraction, and
   COMMS_BOUND verdicts point at knobs this module actually has.

The optimizer update runs in a third program on the synced grads. The
three dispatches are enqueued asynchronously; only the comms phase's
``block_until_ready`` synchronizes (and that is the measurement).

Semantics note: ``loss_fn(params, batch, rng)`` must compute a MEAN over
its batch argument (the ``jit_train_step`` contract) — the mean of
per-slice/per-microbatch means then equals the global mean because every
piece is the same size (divisibility is checked loudly). The rng handed
to each microbatch/slice is a distinct fold of the step rng, so an
rng-using loss sees different draws than the monolithic step; the
equivalence guarantee is for the batch-determined gradient.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, List, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tony_tpu import compat, telemetry
from tony_tpu.parallel.mesh import (BATCH_AXES, replicated_sharding,
                                    tree_batch_shardings)
from tony_tpu.parallel.sharding import DEFAULT_RULES

#: default bucket size (MiB) — matches tony.train.bucket-mb's default.
DEFAULT_BUCKET_MB = 32
#: axes the explicit sync path reduces over; dcn_dp is the multislice
#: axis the whole design aims at, dp rides along where it exists so the
#: in-slice gradient reduction buckets/overlaps the same way.
DEFAULT_SYNC_AXES = ("dcn_dp", "dp")


@dataclasses.dataclass(frozen=True)
class GradSyncSpec:
    """The conf-shaped knobs (``tony.train.*``) in one carryable value."""

    accum_steps: int = 1
    bucket_mb: int = DEFAULT_BUCKET_MB
    matmul_dtype: str = ""

    @classmethod
    def from_conf(cls, conf) -> "GradSyncSpec":
        from tony_tpu.conf import keys as K

        return cls(
            accum_steps=max(1, conf.get_int(K.TRAIN_ACCUM_STEPS, 1)),
            bucket_mb=max(1, conf.get_int(K.TRAIN_BUCKET_MB,
                                          DEFAULT_BUCKET_MB)),
            matmul_dtype=str(conf.get(K.TRAIN_MATMUL_DTYPE, "") or ""))


def plan_buckets(leaf_descs: Sequence[Tuple[Tuple[int, ...], Any]],
                 bucket_mb: int = DEFAULT_BUCKET_MB) -> List[List[int]]:
    """Order-stable bucket plan over flattened grad leaves.

    ``leaf_descs`` is ``[(shape, dtype), ...]`` in tree-flatten order;
    returns a list of buckets, each a list of leaf indices. Greedy in
    order — never reorders leaves, so packing and split-back agree and
    the reduction is deterministic. A bucket closes when it would exceed
    ``bucket_mb`` or when the dtype changes (mixed-dtype grads are never
    silently upcast into one flat buffer). A single leaf larger than the
    bucket gets a bucket of its own (the one-param-spills edge)."""
    cap = max(1, int(bucket_mb)) << 20
    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    cur_dtype = None
    for i, (shape, dtype) in enumerate(leaf_descs):
        dt = jnp.dtype(dtype)
        nbytes = math.prod(shape) * dt.itemsize
        if cur and (dt != cur_dtype or cur_bytes + nbytes > cap):
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
        cur_dtype = dt
    if cur:
        buckets.append(cur)
    return buckets


def bucketed_sync(stacked: Any,
                  bucket_mb: int = DEFAULT_BUCKET_MB,
                  part_sharding: Any = None) -> Any:
    """Mean-reduce per-slice stacked grads ``[n_sync, ...]`` over the
    leading (sync-axes-sharded) dim, bucket by bucket. Jittable; each
    bucket's reduction is an independent collective under SPMD. Returns
    the grads tree without the leading dim — allclose to the monolithic
    psum (same addends, deterministic packing order).

    ``part_sharding`` (a NamedSharding for a [n_sync, elems] part,
    normally ``P(sync_axes, None)``) pins every flattened bucket member
    to ONE layout before packing. On a sharded mesh this is required,
    not cosmetic: grad leaves arrive with heterogeneous layouts
    (fsdp/tp-sharded kernels next to replicated norm scales), and
    concatenating mixed-sharding operands both miscompiles on older jax
    (verified on 0.4.37's CPU SPMD) and would make XLA reshard the
    bucket mid-collective anyway — slice-sharded/replicated-within is
    the layout the DCN all-reduce wants."""
    leaves, treedef = jax.tree.flatten(stacked)
    if not leaves:
        return stacked
    n = leaves[0].shape[0]
    plan = plan_buckets([(l.shape[1:], l.dtype) for l in leaves],
                        bucket_mb)
    out: List[Any] = [None] * len(leaves)

    def flat_part(leaf):
        part = leaf.reshape(n, -1)
        if part_sharding is not None:
            part = jax.lax.with_sharding_constraint(part, part_sharding)
        return part

    for bucket in plan:
        if len(bucket) == 1:
            i = bucket[0]
            inv = jnp.asarray(1.0 / n, leaves[i].dtype)
            out[i] = jnp.sum(leaves[i], axis=0) * inv
            continue
        flat = jnp.concatenate([flat_part(leaves[i]) for i in bucket],
                               axis=1)
        red = jnp.sum(flat, axis=0) * jnp.asarray(1.0 / n, flat.dtype)
        off = 0
        for i in bucket:
            shape = leaves[i].shape[1:]
            size = math.prod(shape)
            out[i] = red[off:off + size].reshape(shape)
            off += size
    return treedef.unflatten(out)


def monolithic_grads(loss_fn: Callable, params: Any, batch: Any,
                     rng: jax.Array,
                     rules: Sequence[Tuple[str, Any]] = DEFAULT_RULES
                     ) -> Any:
    """The reference the bucketed path is tested against: one global-mean
    loss, XLA's own end-of-backward reduction. Call under jit/set_mesh."""
    def global_loss(p):
        with nn.logical_axis_rules(list(rules)):
            loss, _ = loss_fn(p, batch, rng)
        return loss

    return jax.grad(global_loss)(params)


def _sync_sizes(mesh: Mesh, sync_axes: Sequence[str]) -> int:
    shape = dict(mesh.shape)
    bad = [a for a in sync_axes if a not in shape]
    if bad:
        raise ValueError(f"sync axes {bad} not in mesh axes "
                         f"{sorted(shape)}")
    not_batch = [a for a in sync_axes if a not in BATCH_AXES]
    if not_batch:
        raise ValueError(
            f"sync axes must be pure data-parallel batch axes "
            f"(params replicated over them); {not_batch} are not in "
            f"{BATCH_AXES}")
    return math.prod(shape[a] for a in sync_axes)


def stacked_grad_shardings(mesh: Mesh, param_shardings: Any,
                           sync_axes: Sequence[str]) -> Any:
    """Shardings for the stacked per-slice grads: each param leaf's spec
    gains a leading dim split over the sync axes."""
    axes = tuple(sync_axes)

    def one(sh):
        spec = tuple(sh.spec) if isinstance(sh, NamedSharding) else ()
        return NamedSharding(mesh, P(axes, *spec))

    return jax.tree.map(one, param_shardings)


def _build_accum_fn(loss_fn: Callable, mesh: Mesh, accum_steps: int,
                    n_sync: int, sync_axes: Tuple[str, ...],
                    rules: Sequence[Tuple[str, Any]]):
    """accum(params, batch, rng) -> (stacked_grads, loss, aux): scan A
    microbatches, vmap per sync slice, accumulate locally — no
    cross-slice collective anywhere in this program."""
    local_axes = tuple(a for a in BATCH_AXES if a not in sync_axes)

    def ruled_loss(p, b, r):
        with nn.logical_axis_rules(list(rules)):
            return loss_fn(p, b, r)

    def accum(params, batch, rng):
        leaves, treedef = jax.tree.flatten(batch)
        is_scalar = [jnp.ndim(l) == 0 for l in leaves]
        batched = []
        for leaf, scalar in zip(leaves, is_scalar):
            if scalar:
                continue
            gb = leaf.shape[0]
            if gb % (n_sync * accum_steps):
                raise ValueError(
                    f"global batch {gb} not divisible by "
                    f"sync slices ({n_sync} over {sync_axes}) x "
                    f"tony.train.accum-steps ({accum_steps})")
            local = gb // (n_sync * accum_steps)
            x = leaf.reshape((n_sync, accum_steps, local)
                             + leaf.shape[1:])
            x = jnp.moveaxis(x, 1, 0)       # [A, n_sync, local, ...]
            x = jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, P(None, sync_axes,
                                         local_axes or None,
                                         *([None] * (leaf.ndim - 1)))))
            batched.append(x)
        scalars = [l for l, s in zip(leaves, is_scalar) if s]

        def rebuild(micro_batched):
            it_b = iter(micro_batched)
            it_s = iter(scalars)
            return treedef.unflatten(
                [next(it_s) if s else next(it_b) for s in is_scalar])

        vmap_axes = treedef.unflatten(
            [None if s else 0 for s in is_scalar])
        keys = jax.random.split(rng, accum_steps * n_sync)
        keys = keys.reshape((accum_steps, n_sync) + keys.shape[1:])

        grad_one = jax.vmap(
            jax.value_and_grad(ruled_loss, has_aux=True),
            in_axes=(None, vmap_axes, 0))

        zeros = jax.tree.map(
            lambda p: jnp.zeros((n_sync,) + p.shape, p.dtype), params)

        def body(acc, xs):
            ks, micro = xs
            (l, aux), g = grad_one(params, rebuild(list(micro)), ks)
            return jax.tree.map(jnp.add, acc, g), (l, aux)

        stacked, (losses, auxes) = jax.lax.scan(
            body, zeros, (keys, tuple(batched)))
        stacked = jax.tree.map(
            lambda g: g * jnp.asarray(1.0 / accum_steps, g.dtype),
            stacked)
        loss = jnp.mean(losses)
        aux = jax.tree.map(jnp.mean, auxes)
        return stacked, loss, aux

    return accum


def jit_train_step_accum(
    loss_fn: Callable[[Any, Any, jax.Array], Tuple[jax.Array, dict]],
    mesh: Mesh,
    state_shardings: Any,
    sample_batch: Any,
    *,
    accum_steps: int = 1,
    bucket_mb: int = DEFAULT_BUCKET_MB,
    sync_axes: Sequence[str] = DEFAULT_SYNC_AXES,
    rules: Sequence[Tuple[str, Any]] = DEFAULT_RULES,
    donate: bool = True,
    comms_phase: bool = True,
):
    """The grad-sync twin of ``jit_train_step``: same signature for the
    returned ``step(state, batch, rng) -> (state, metrics)``, but the
    gradient path is microbatched (``accum_steps``), explicitly
    cross-slice-synced bucket-by-bucket (``bucket_mb`` MiB over
    ``sync_axes``), and the sync dispatch is wrapped in
    ``telemetry.phase("comms")`` so the DCN wait is attributable.

    ``sync_axes`` defaults to ``("dcn_dp", "dp")`` — the pure
    data-parallel axes over which params are replicated (``fsdp`` stays
    with XLA's automatic reduction: its params are sharded, so the
    per-slice vmap would replicate them). Axes of size 1 cost nothing.
    """
    sync_axes = tuple(sync_axes)
    n_sync = _sync_sizes(mesh, sync_axes)
    accum_steps = max(1, int(accum_steps))

    param_sh = state_shardings.params
    stacked_sh = stacked_grad_shardings(mesh, param_sh, sync_axes)
    batch_sh = tree_batch_shardings(mesh, sample_batch)
    rep = replicated_sharding(mesh)

    accum_jit = jax.jit(
        _build_accum_fn(loss_fn, mesh, accum_steps, n_sync, sync_axes,
                        rules),
        in_shardings=(param_sh, batch_sh, rep),
        out_shardings=(stacked_sh, rep, rep))

    # No donation here: the [n_sync, ...] inputs can never alias the
    # reduced outputs (different shapes), so donating would only emit
    # XLA's unusable-donation warning on every compile.
    part_sh = NamedSharding(mesh, P(sync_axes, None))
    sync_jit = jax.jit(
        lambda stacked: bucketed_sync(stacked, bucket_mb,
                                      part_sharding=part_sh),
        in_shardings=(stacked_sh,),
        out_shardings=param_sh)

    def apply_fn(state, grads, loss, aux):
        new_state = state.apply_gradients(grads)
        metrics = {"loss": loss, "step": new_state.step, **aux}
        return new_state, metrics

    apply_jit = jax.jit(
        apply_fn,
        in_shardings=(state_shardings, param_sh, rep, rep),
        out_shardings=(state_shardings, rep),
        donate_argnums=(0, 1) if donate else ())

    def step(state, batch, rng):
        with compat.set_mesh(mesh):
            stacked, loss, aux = accum_jit(state.params, batch, rng)
            if comms_phase:
                with telemetry.phase("comms") as p:
                    grads = sync_jit(stacked)
                    p.block_until_ready(grads)
            else:
                grads = sync_jit(stacked)
            return apply_jit(state, grads, loss, aux)

    return step
