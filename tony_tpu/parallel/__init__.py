"""Parallelism library: device meshes, sharding rules, distributed transforms.

This subsystem is **new work relative to the reference**: TonY has no
tensor/pipeline/sequence/expert parallelism anywhere (verified in SURVEY.md
§2.3 — the reference only orchestrates process gangs and delegates all
sharding to the user's ML framework). In a TPU-native design the framework
owns the device mesh and the sharding of every tensor, because the data plane
(XLA collectives over ICI/DCN) and the orchestration plane meet in the same
compiled program.
"""

from tony_tpu.parallel.mesh import (  # noqa: F401
    MESH_AXES, MeshSpec, batch_sharding, build_mesh, replicated_sharding,
)
from tony_tpu.parallel.sharding import (  # noqa: F401
    DEFAULT_RULES, logical_sharding, param_shardings, with_rules,
)
from tony_tpu.parallel.train import (  # noqa: F401
    TrainState, init_sharded_state, jit_train_step,
)
from tony_tpu.parallel.grad_sync import (  # noqa: F401
    GradSyncSpec, bucketed_sync, jit_train_step_accum, monolithic_grads,
    plan_buckets,
)
