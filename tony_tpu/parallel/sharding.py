"""Logical-axis sharding rules: name tensor dimensions, map names to mesh axes.

Models annotate weights with *logical* axis names (``embed``, ``mlp``,
``heads``…) via ``flax.linen.with_logical_partitioning``; one rules table maps
those names onto the physical mesh axes of `tony_tpu.parallel.mesh`. Changing
the parallelism strategy = changing the table, never the model. (The scaling
book's "annotate shardings, let XLA insert collectives" recipe.)

The reference has no analogue — its sharding story is "hand each task a
host:port list and hope the user framework sorts it out"
(``TonySession.java:226-246``).
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tony_tpu.parallel.mesh import BATCH_AXES

# Logical name → mesh axis (or tuple of axes). Maxtext-style assignment:
# batch over dp+fsdp, params sharded over fsdp (FSDP) with the model
# dimension split over tp, sequence over sp.
DEFAULT_RULES: Tuple[Tuple[str, Any], ...] = (
    ("batch", BATCH_AXES),
    ("seq", "sp"),
    ("embed", "fsdp"),
    ("mlp", "tp"),
    ("heads", "tp"),
    ("kv_heads", "tp"),
    ("kv", None),
    ("qkv", None),
    ("vocab", "tp"),
    # Embedding-table dims (see models/transformer.py): vocab rows over
    # both model axes, embed dim whole — the gather then partitions as
    # masked-lookup + all-reduce instead of an embed-sharded output that
    # SPMD can only reshard by full rematerialization.
    ("vocab_table", ("tp", "fsdp")),
    ("embed_table", None),
    ("layers", None),
    ("stage", "pp"),
    ("expert", "ep"),
    ("expert_logits", None),
    ("norm", None),
)


def with_rules(rules: Sequence[Tuple[str, Any]] = DEFAULT_RULES):
    """Context manager activating logical rules for flax's
    `with_logical_constraint` calls inside model code."""
    return nn.logical_axis_rules(rules)


def logical_sharding(mesh: Mesh, *logical_axes: str,
                     rules: Sequence[Tuple[str, Any]] = DEFAULT_RULES
                     ) -> NamedSharding:
    """NamedSharding for a tensor whose dims carry the given logical names."""
    spec = nn.logical_to_mesh_axes(logical_axes, rules=list(rules))
    return NamedSharding(mesh, spec)


def reshard(tree: Any, shardings: Any) -> Any:
    """Re-lay an in-memory pytree onto new shardings — the elastic
    re-mesh path when state survives in host memory rather than on disk
    (checkpoint restore covers the on-disk path: orbax's StandardRestore
    re-lays-out onto whatever mesh the target shardings name).
    ``jax.device_put`` moves each leaf shard-by-shard; cross-mesh moves
    stage through host where devices disagree, which is exactly the
    shrink/grow case."""
    return jax.tree.map(lambda x, s: jax.device_put(x, s), tree,
                        shardings)


def param_shardings(mesh: Mesh, abstract_tree: Any,
                    rules: Sequence[Tuple[str, Any]] = DEFAULT_RULES) -> Any:
    """Map a tree of flax ``Partitioned`` metadata (from ``jax.eval_shape`` of
    ``model.init``) to a tree of NamedShardings. Leaves without metadata are
    replicated."""
    spec_tree = nn.get_partition_spec(abstract_tree)
    logical = nn.logical_to_mesh(spec_tree, rules=list(rules))

    def to_sharding(spec):
        if not isinstance(spec, P):
            spec = P()
        return NamedSharding(mesh, spec)

    return jax.tree.map(
        to_sharding, logical,
        is_leaf=lambda x: isinstance(x, P) or x is None)
