"""Notebook mode: run a notebook/server command as a single-node job and
tunnel a local port to it.

Reference: ``NotebookSubmitter.java`` — Jupyter as a single-container app
(:46), poll TaskInfos for the notebook task's endpoint, then start a local
``ProxyServer`` so the user's browser reaches it (:118-139). Here the
"container" is the coordinator-local single-node path
(``Coordinator._do_local_job``): the command runs with ``TB_PORT`` set to
a reserved port and the coordinator registers ``http://host:port`` as the
job's url, which the client sees in every application report.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional
from urllib.parse import urlparse

from tony_tpu.client import TaskUpdateListener, TonyTpuClient
from tony_tpu.conf import keys as K
from tony_tpu.proxy import ProxyServer

log = logging.getLogger(__name__)

# --ip=0.0.0.0: the registered url and the proxy target the HOSTNAME
# (the notebook may run on a remote coordinator host), so loopback-only
# binding would make the tunnel connect-refused on any multi-homed host.
DEFAULT_NOTEBOOK_CMD = (
    "jupyter notebook --no-browser --ip=0.0.0.0 --port=$TB_PORT "
    "--NotebookApp.token='' --NotebookApp.password=''")


class NotebookProxyListener(TaskUpdateListener):
    """Starts the local proxy as soon as the report carries the server
    url; fires ``ready`` with the proxied local port."""

    def __init__(self, local_port: int = 0):
        self.local_port = local_port
        self.proxy: Optional[ProxyServer] = None
        self.ready = threading.Event()

    def on_application_report(self, report: dict) -> None:
        url = report.get("tb_url") or ""
        if not url or self.proxy is not None:
            return
        p = urlparse(url)
        if not p.hostname or not p.port:
            log.warning("notebook url %r has no host:port", url)
            return
        self.proxy = ProxyServer(p.hostname, p.port,
                                 local_port=self.local_port).start()
        print(f"notebook available at http://127.0.0.1:{self.proxy.port} "
              f"(proxied to {p.hostname}:{p.port})")
        self.ready.set()

    def on_application_finished(self, status: str, report: dict) -> None:
        if self.proxy is not None:
            self.proxy.stop()


def submit_notebook(conf, workdir: Optional[str] = None,
                    command: str = "", local_port: int = 0,
                    extra_listener: Optional[TaskUpdateListener] = None
                    ) -> int:
    """Submit the notebook job and block until it ends (the user stops the
    server / kills the CLI). Returns the job exit code."""
    conf.set(K.COORDINATOR_COMMAND, command or DEFAULT_NOTEBOOK_CMD)
    client = TonyTpuClient(conf, workdir=workdir)
    client.add_listener(NotebookProxyListener(local_port))
    if extra_listener is not None:
        client.add_listener(extra_listener)
    return client.start()
