"""GCE/TPU-VM preemption-notice watcher: turn the platform's advance
warning into the kill chain's TERM-grace path.

Preemptible/spot TPU VMs get an advance notice before the machine is
reclaimed: the metadata server's ``instance/preempted`` value flips to
``TRUE`` (readable with ``?wait_for_change=true`` as a hanging GET).
Without a watcher that warning is wasted and the job experiences
preemption as sudden SIGKILL — resume rolls back to the last periodic
checkpoint. With it, the executor delivers SIGTERM to the user process
group the moment the notice lands, so a
``CheckpointManager.install_preemption_handler`` save runs inside the
warning window and the retried job resumes at the exact step.

The reference has no analogue (YARN nodes aren't preemptible mid-lease
the way spot TPU VMs are); the closest is its decommission handling via
NM shutdown. This is the TPU-native completion of the story:

    metadata notice → SIGTERM user group → final durable save →
    host dies → slice lease invalid → coordinator retries on a fresh
    lease → script restores latest_step().

Off-GCP the first metadata probe fails (no such host) and the watcher
disables itself silently — zero cost outside the cloud. Tests point
``TONY_METADATA_ENDPOINT`` at an in-process HTTP server.
"""

from __future__ import annotations

import logging
import os
import signal
import threading
from typing import Callable, Optional
from urllib import error as urlerror
from urllib import request as urlrequest

log = logging.getLogger(__name__)

METADATA_ENDPOINT_ENV = "TONY_METADATA_ENDPOINT"
#: set to "0" to disable the watcher entirely
PREEMPTION_WATCH_ENV = "TONY_PREEMPTION_WATCH"
_DEFAULT_ENDPOINT = "http://metadata.google.internal"
_PREEMPTED_PATH = "/computeMetadata/v1/instance/preempted"


class PreemptionWatcher(threading.Thread):
    """Daemon thread: hanging-GET the preempted flag; fire once on TRUE.

    ``on_preempt`` runs on this thread exactly once. The default action
    (see ``start_for_executor``) TERMs the user process group — the same
    signal path as a graceful teardown, so everything downstream
    (handler saves, exit-code reporting, retry) is already tested.
    """

    def __init__(self, on_preempt: Callable[[], None],
                 endpoint: Optional[str] = None,
                 poll_interval_s: float = 5.0):
        super().__init__(name="tony-preemption-watcher", daemon=True)
        self.endpoint = (endpoint
                         or os.environ.get(METADATA_ENDPOINT_ENV)
                         or _DEFAULT_ENDPOINT).rstrip("/")
        self._on_preempt = on_preempt
        self._poll_interval_s = poll_interval_s
        self._stop_evt = threading.Event()
        self.fired = False

    def _probe(self, wait: bool, etag: str = ""):
        """(value, etag). With ``wait`` + a last_etag, GCE parks the GET
        until the value CHANGES FROM THAT ETAG — closing the race where
        the flag flips between a plain read and the next hanging GET (a
        hang keyed only on "next change" would then wait forever while
        the ~30 s spot warning burns)."""
        q = ""
        if wait:
            q = "?wait_for_change=true" + (
                f"&last_etag={etag}" if etag else "")
        req = urlrequest.Request(self.endpoint + _PREEMPTED_PATH + q,
                                 headers={"Metadata-Flavor": "Google"})
        with urlrequest.urlopen(req, timeout=300 if wait else 5) as r:
            return (r.read().decode().strip().upper(),
                    r.headers.get("ETag", "") or "")

    @staticmethod
    def _decisively_absent(err: Exception) -> bool:
        """No-such-host / connection-refused = not on GCE (normal, stay
        quiet); anything else may be a transient on a real TPU VM and
        must NOT silently disable spot protection."""
        import socket as socketlib

        reason = getattr(err, "reason", err)
        return isinstance(reason, (socketlib.gaierror,
                                   ConnectionRefusedError))

    def _initial_probe(self):
        failures = 0
        while not self._stop_evt.is_set():
            try:
                return self._probe(wait=False)
            except (urlerror.URLError, OSError, ValueError) as e:
                if self._decisively_absent(e):
                    log.debug("no metadata server at %s; preemption "
                              "watcher off", self.endpoint)
                    return None, ""
                failures += 1
                if failures >= 3:
                    log.warning(
                        "metadata server at %s unreachable after %d "
                        "attempts (%s) — preemption watcher DISABLED; "
                        "spot reclaim will arrive as SIGKILL",
                        self.endpoint, failures, e)
                    return None, ""
                if self._stop_evt.wait(self._poll_interval_s):
                    return None, ""
        return None, ""

    def run(self) -> None:
        import time as _time

        value, etag = self._initial_probe()
        if value is None:
            return
        while not self._stop_evt.is_set():
            if value == "TRUE":
                self.fired = True
                log.warning("PREEMPTION NOTICE from %s — signalling the "
                            "user process for a final checkpoint",
                            self.endpoint)
                try:
                    self._on_preempt()
                except Exception:  # noqa: BLE001 — never kill the thread
                    log.exception("preemption action failed")
                return
            t0 = _time.monotonic()
            try:
                value, etag = self._probe(wait=True, etag=etag)
            except (urlerror.URLError, OSError, ValueError):
                # transient metadata hiccup (or hanging-GET timeout):
                # back off and re-poll rather than dying
                if self._stop_evt.wait(self._poll_interval_s):
                    return
                try:
                    value, etag = self._probe(wait=False)
                except (urlerror.URLError, OSError, ValueError):
                    value = ""
                continue
            if value != "TRUE" and _time.monotonic() - t0 < 0.5:
                # A "hanging" GET that returns unchanged instantly is a
                # misbehaving proxy; don't let it become a busy spin.
                if self._stop_evt.wait(self._poll_interval_s):
                    return

    def stop(self) -> None:
        # NB: named _stop_evt, not _stop — threading.Thread has a private
        # _stop() method that an attribute would shadow (join() crashes).
        self._stop_evt.set()


def start_for_executor(user_proc_ref) -> Optional[PreemptionWatcher]:
    """Start the watcher wired to TERM the executor's user process group.

    ``user_proc_ref`` is the executor's mutable ``[Popen]`` holder (the
    user command may not have started yet when the watcher does). No-op
    (returns None) when disabled via TONY_PREEMPTION_WATCH=0."""
    if os.environ.get(PREEMPTION_WATCH_ENV, "1") == "0":
        return None

    def _term_user_group() -> None:
        p = user_proc_ref[0] if user_proc_ref else None
        if p is not None and p.poll() is None:
            try:
                os.killpg(p.pid, signal.SIGTERM)
                return
            except (ProcessLookupError, PermissionError):
                pass
        # User command not running (yet/anymore): nothing to save —
        # let the platform's reclaim take its course.
        log.warning("preemption notice with no running user process")

    w = PreemptionWatcher(_term_user_group)
    w.start()
    return w
