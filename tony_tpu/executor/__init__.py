from tony_tpu.executor.executor import TaskExecutor  # noqa: F401
