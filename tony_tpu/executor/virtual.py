"""Beat-only virtual executors: the control-plane width harness.

Every drill so far ran ≤8 virtual hosts because each task cost a whole
executor subprocess plus a user process. This module keeps everything
the CONTROL PLANE sees — real RPC frames over real TCP (register →
barrier poll → heartbeat with a progress/metrics beacon → execution
result), real journal records, real fencing (session epoch, membership
generation) — and drops everything it doesn't: no subprocess, no user
command, no ports, no localization. One :class:`VirtualGang` multiplexes
hundreds of virtual tasks over a small beat pump (a deadline heap +
``tony.scale.virtual-pump-threads`` worker threads, one RPC connection
per worker), so 128–1024 registered tasks per box fit in CI-sized time
— the width at which the coordinator's O(n)-per-tick loops
(coordinator/coordphases.py) become measurable.

Task state machine (one RPC call per firing, rescheduled on the heap):

    register --(spec != None: barrier open)--> beat --(run_s up)--> finish
        ^                                       |
        '----(resize directive: park under new mgen)

A ``release`` resize directive ends the task unreported with exit 143
(exactly what a real released executor does); fencing errors
(FencedError / StaleGenerationError) are terminal without a report, like
a real executor's teardown. ``register_execution_result`` carries exit 0
when ``run_s`` elapses — jobs built on virtual gangs SUCCEED through the
ordinary completion path.
"""

from __future__ import annotations

import heapq
import logging
import threading
import time
from typing import Dict, Optional

from tony_tpu import faults
from tony_tpu.rpc.wire import FencedError, RpcClient

log = logging.getLogger(__name__)

#: task states
_REGISTER = "register"
_BEAT = "beat"
_FINISH = "finish"


class VirtualTaskHandle:
    """Popen-shaped handle for the backend: ``poll()`` returns the final
    exit code once the virtual task ended, else None."""

    def __init__(self, task_id: str):
        self.task_id = task_id
        self.returncode: Optional[int] = None

    def poll(self) -> Optional[int]:
        return self.returncode


class _VTask:
    def __init__(self, task_id: str, session_id: int, mgen: int,
                 seq: int):
        self.task_id = task_id
        self.session_id = session_id
        self.mgen = mgen
        self.seq = seq
        self.state = _REGISTER
        self.handle = VirtualTaskHandle(task_id)
        self.started = time.monotonic()
        self.beat_t0: Optional[float] = None   # set when the barrier opens
        self.errors = 0

    @property
    def done(self) -> bool:
        return self.handle.returncode is not None


class _Clients(threading.local):
    client: Optional[RpcClient] = None


class VirtualGang:
    """Shared beat pump for one coordinator's virtual tasks."""

    #: consecutive RPC failures before a virtual task is declared dead
    #: (exit 137 — the vanished-host shape the coordinator must absorb
    #: or fail exactly like a real loss).
    MAX_ERRORS = 3

    def __init__(self, host: str, port: int, token: Optional[str] = None,
                 generation: int = 0, hb_interval_s: float = 1.0,
                 steps_per_s: float = 5.0, run_s: float = 0.0,
                 pump_threads: int = 8):
        self._addr = (host, int(port))
        self._token = token or None
        self._generation = int(generation)
        self.hb_interval_s = max(0.05, float(hb_interval_s))
        self.steps_per_s = float(steps_per_s)
        self.run_s = float(run_s)
        self._pump_threads = max(1, int(pump_threads))
        self._tasks: Dict[str, _VTask] = {}
        self._heap: list = []          # (deadline, seq, task_id)
        self._cv = threading.Condition()
        self._stopping = False
        self._seq = 0
        self._threads: list = []
        self._tls = _Clients()

    # -- lifecycle --------------------------------------------------------
    def launch(self, task_id: str, session_id: int = 0,
               mgen: int = -1) -> VirtualTaskHandle:
        with self._cv:
            self._seq += 1
            task = _VTask(task_id, int(session_id), int(mgen), self._seq)
            self._tasks[task_id] = task
            # Deterministic stagger: registrations spread over one beat
            # interval instead of arriving in lockstep (a gang-sized
            # thundering herd would measure the herd, not the plane).
            delay = (self._seq % 97) * (self.hb_interval_s / 97.0)
            heapq.heappush(self._heap,
                           (time.monotonic() + delay, self._seq, task_id))
            self._ensure_threads()
            self._cv.notify()
        return task.handle

    def kill(self, task_id: str, exit_code: int = 143) -> None:
        """Backend kill: the virtual task stops calling home and reads as
        exited-by-signal (the TERM shape by default)."""
        with self._cv:
            task = self._tasks.get(task_id)
            if task is not None and not task.done:
                task.handle.returncode = exit_code

    def stop(self) -> None:
        with self._cv:
            self._stopping = True
            for task in self._tasks.values():
                if not task.done:
                    task.handle.returncode = 143
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=2)

    def live_count(self) -> int:
        with self._cv:
            return sum(1 for t in self._tasks.values() if not t.done)

    # -- pump -------------------------------------------------------------
    def _ensure_threads(self) -> None:
        while len(self._threads) < self._pump_threads:
            t = threading.Thread(
                target=self._worker, daemon=True,
                name=f"virtual-pump-{len(self._threads)}")
            self._threads.append(t)
            t.start()

    def _client(self) -> RpcClient:
        if self._tls.client is None:
            self._tls.client = RpcClient(
                self._addr[0], self._addr[1], token=self._token,
                generation=self._generation, max_retries=2,
                retry_sleep_s=0.2, call_timeout_s=30.0,
                peer="coordinator")
        return self._tls.client

    def _worker(self) -> None:
        while True:
            with self._cv:
                while True:
                    if self._stopping:
                        return
                    now = time.monotonic()
                    if self._heap and self._heap[0][0] <= now:
                        _, _, task_id = heapq.heappop(self._heap)
                        task = self._tasks.get(task_id)
                        break
                    timeout = (self._heap[0][0] - now) if self._heap \
                        else 0.5
                    self._cv.wait(timeout=min(max(timeout, 0.0), 0.5))
            if task is None or task.done:
                continue
            try:
                next_in = self._fire(task)
            except Exception:  # noqa: BLE001 — the pump must survive
                log.exception("virtual task %s pump error", task.task_id)
                next_in = self.hb_interval_s
            if next_in is None or task.done:
                continue
            with self._cv:
                self._seq += 1
                heapq.heappush(
                    self._heap,
                    (time.monotonic() + next_in, self._seq,
                     task.task_id))
                self._cv.notify()

    def _apply_directives(self, task: _VTask, resp) -> Optional[float]:
        """Fold a heartbeat response's directives into the task's state
        machine. Returns the next-fire delay when a directive decided it
        (park / release), else None (the caller continues as usual)."""
        if not isinstance(resp, dict):
            return None
        rz = resp.get("resize")
        if isinstance(rz, dict) and int(rz.get("mgen", -1)) > task.mgen:
            task.mgen = int(rz["mgen"])
            if rz.get("action") == "release":
                # Released members exit 143 unreported, like the real
                # executor's release path.
                task.handle.returncode = 143
                return None
            # Drain: "TERM the user process" is a no-op here; park =
            # re-register under the new generation, promptly.
            task.state = _REGISTER
            return 0.05
        return None

    # -- one state-machine step ------------------------------------------
    def _fire(self, task: _VTask) -> Optional[float]:
        client = self._client()
        job, _, index = task.task_id.partition(":")
        try:
            if task.state == _REGISTER:
                # Host/port are synthetic but structurally real: the
                # cluster spec the barrier broadcasts is built from them.
                spec = client.call(
                    "register_worker_spec", task_id=task.task_id,
                    host=f"vh-{index}", port=20000 + int(index or 0),
                    session_id=task.session_id, mgen=task.mgen)
                if spec is None:
                    # Barrier still closed. Beat anyway, like the real
                    # executor (its Heartbeater starts BEFORE
                    # registration): the resize directive rides
                    # heartbeat responses, and a task that only polled
                    # the barrier could never learn the membership
                    # generation a drain is waiting for it to park
                    # under — a deadlock the real stack cannot have.
                    resp = client.call("task_executor_heartbeat",
                                       task_id=task.task_id,
                                       session_id=task.session_id,
                                       mgen=task.mgen)
                    self._apply_directives(task, resp)
                    return self.hb_interval_s
                task.state = _BEAT
                if task.beat_t0 is None:
                    task.beat_t0 = time.monotonic()
                task.errors = 0
                return self.hb_interval_s
            if task.state == _FINISH:
                client.call("register_execution_result",
                            task_id=task.task_id, exit_code=0,
                            session_id=task.session_id)
                task.handle.returncode = 0
                return None
            # _BEAT: one heartbeat with a synthetic progress beacon —
            # real beacon_fold work for the coordinator, real liveness.
            # host.loss here mirrors the real executor's heartbeat-loop
            # poll (executor.py): a firing kills THIS virtual host with
            # the vanished-host exit shape. ``task:*`` correlates the
            # loss across hosts — the chaos planner's multi-host-death
            # schedules ride this one site.
            if faults.fire("host.loss", task_id=task.task_id):
                log.warning("FAULT host.loss: virtual task %s vanishes",
                            task.task_id)
                task.handle.returncode = 137
                return None
            steps = self.steps_per_s * (time.monotonic()
                                        - (task.beat_t0 or task.started))
            progress = {"steps": round(steps, 2), "age_s": 0.0,
                        "metrics": {"steps_per_sec": self.steps_per_s}}
            resp = client.call("task_executor_heartbeat",
                               task_id=task.task_id,
                               session_id=task.session_id,
                               progress=progress, mgen=task.mgen)
            task.errors = 0
            next_in = self._apply_directives(task, resp)
            if next_in is not None or task.done:
                return next_in
            if self.run_s and time.monotonic() - task.started \
                    >= self.run_s:
                task.state = _FINISH
                return 0.0
            return self.hb_interval_s
        except FencedError as e:
            # Terminal verdict about this task's topology/epoch — tear
            # down without a report, exactly like a fenced executor.
            log.info("virtual task %s fenced: %s", task.task_id, e)
            task.handle.returncode = 143
            # The fenced client connection is closed; drop it so the
            # worker's next task gets a fresh one.
            self._tls.client = None
            return None
        except Exception as e:  # noqa: BLE001 — RPC trouble is survivable
            task.errors += 1
            self._tls.client = None
            if task.errors >= self.MAX_ERRORS:
                log.warning("virtual task %s giving up after %d RPC "
                            "failures: %s", task.task_id, task.errors, e)
                task.handle.returncode = 137     # vanished-host shape
                return None
            return self.hb_interval_s
