"""Per-task resource metrics sampler.

Reference model: ``TaskMonitor.java`` (192 LoC) — samples process-tree RSS via
YARN's ResourceCalculatorProcessTree (:71,:109-114) and GPU utilization via
``nvidia-smi -x -q`` (``GpuDiscoverer.java:88-131``), keeps max/avg aggregates
(:172-186), and pushes MetricsWritable to the AM every
``tony.task.metrics-interval-ms`` (:92-99).

TPU deltas: RSS comes from /proc (no YARN); accelerator telemetry comes from
the TPU runtime when present — libtpu exposes device metrics through JAX
(``jax.local_devices()[i].memory_stats()``) instead of an ``nvidia-smi``
subprocess. Sampling is best-effort and never fails the task.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional

MAX_MEMORY_BYTES = "MAX_MEMORY_BYTES"
AVG_MEMORY_BYTES = "AVG_MEMORY_BYTES"
MAX_TPU_HBM_BYTES = "MAX_TPU_HBM_BYTES"
AVG_TPU_HBM_BYTES = "AVG_TPU_HBM_BYTES"
USER_DEVICE_COUNT = "USER_DEVICE_COUNT"
# Utilization, derived in the user process by telemetry.step() wrappers
# (the TPU stand-in for the reference's nvidia-smi duty-cycle sampling,
# TaskMonitor.java:116-170): latest-value passthrough, not max/avg.
STEPS_PER_SEC = "STEPS_PER_SEC"
STEP_DUTY_CYCLE = "STEP_DUTY_CYCLE"
MODEL_FLOPS_PER_SEC = "MODEL_FLOPS_PER_SEC"
MFU = "MFU"
# Final step count: the same counter the executor's progress beacon rides
# on heartbeats (hang detection, coordinator/liveness.py) — in the final
# metrics it lets a postmortem line up "steps done" with the step rate.
STEPS_COMPLETED = "STEPS_COMPLETED"
_UTIL_PASSTHROUGH = {
    STEPS_PER_SEC: "steps_per_sec",
    STEP_DUTY_CYCLE: "step_duty_cycle",
    MODEL_FLOPS_PER_SEC: "model_flops_per_sec",
    MFU: "mfu_vs_peak_bf16",
    STEPS_COMPLETED: "steps_completed",
}


def _proc_tree_rss_bytes(root_pid: int) -> int:
    """Sum VmRSS over root_pid and its descendants (the
    ResourceCalculatorProcessTree analogue)."""
    children: Dict[int, List[int]] = {}
    try:
        for entry in os.listdir("/proc"):
            if not entry.isdigit():
                continue
            try:
                with open(f"/proc/{entry}/stat") as f:
                    parts = f.read().rsplit(") ", 1)[-1].split()
                ppid = int(parts[1])
                children.setdefault(ppid, []).append(int(entry))
            except (OSError, ValueError, IndexError):
                continue
    except OSError:
        return 0
    total = 0
    stack = [root_pid]
    seen = set()
    while stack:
        pid = stack.pop()
        if pid in seen:
            continue
        seen.add(pid)
        try:
            with open(f"/proc/{pid}/status") as f:
                for line in f:
                    if line.startswith("VmRSS:"):
                        total += int(line.split()[1]) * 1024
                        break
        except (OSError, ValueError):
            pass
        stack.extend(children.get(pid, []))
    return total


def tpu_hbm_in_use_bytes() -> int:
    """Best-effort HBM usage of locally visible TPU devices; 0 when no TPU
    runtime is attached to *this* process (the usual case — the user process
    owns the chips)."""
    try:
        import sys

        if "jax" not in sys.modules:
            # The probe is only meaningful where this process already runs
            # jax (in-process/notebook modes). IMPORTING jax here costs
            # ~2.3 s and then reads 0 — in the executor that tax landed in
            # monitor.stop()'s final sample, i.e. on EVERY task teardown
            # (found via the r5 suite-latency hunt: a trivial task's
            # "user process exited" trailed its actual exit by 2.3 s).
            return 0
        import jax

        total = 0
        for d in jax.local_devices():
            stats = getattr(d, "memory_stats", lambda: None)()
            if stats:
                total += int(stats.get("bytes_in_use", 0))
        return total
    except Exception:  # noqa: BLE001 — telemetry must never break the task
        return 0


class TaskMonitor:
    """Background sampler pushing metrics to the coordinator."""

    def __init__(self, task_id: str, push: Callable[[str, dict], None],
                 interval_s: float = 5.0,
                 pid_fn: Optional[Callable[[], Optional[int]]] = None,
                 metrics_file: Optional[str] = None):
        self.task_id = task_id
        self._push = push
        self._interval_s = interval_s
        self._pid_fn = pid_fn or (lambda: os.getpid())
        self._metrics_file = metrics_file
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._samples = 0
        # Latest raw RSS sample (not max/avg): the live-metrics beacon
        # reads it so `tony-tpu top` shows current memory, not the peak.
        self.last_rss = 0.0
        self._metrics: Dict[str, float] = {
            MAX_MEMORY_BYTES: 0.0, AVG_MEMORY_BYTES: 0.0,
            MAX_TPU_HBM_BYTES: 0.0, AVG_TPU_HBM_BYTES: 0.0,
            USER_DEVICE_COUNT: 0.0,
        }

    def sample_once(self) -> Dict[str, float]:
        pid = self._pid_fn()
        rss = _proc_tree_rss_bytes(pid) if pid else 0
        # HBM: prefer the user process's own reporter (tony_tpu.telemetry
        # writes TONY_METRICS_FILE from inside the process that owns the
        # chips); the local probe only ever sees this monitor process and
        # reads 0 on real jobs (round-1 VERDICT weak #7).
        hbm = 0.0
        if self._metrics_file:
            from tony_tpu.telemetry import read_stats

            stats = read_stats(self._metrics_file)
            hbm = float(stats.get("hbm_bytes_in_use", 0) or 0)
            self._metrics[USER_DEVICE_COUNT] = max(
                self._metrics[USER_DEVICE_COUNT],
                float(stats.get("device_count", 0) or 0))
            for key, src in _UTIL_PASSTHROUGH.items():
                if src in stats:
                    self._metrics[key] = float(stats[src])
        if not hbm:
            hbm = tpu_hbm_in_use_bytes()
        self.last_rss = float(rss)
        self._samples += 1
        n = self._samples
        # max/avg aggregation (reference TaskMonitor.java:172-186).
        m = self._metrics
        m[MAX_MEMORY_BYTES] = max(m[MAX_MEMORY_BYTES], rss)
        m[AVG_MEMORY_BYTES] += (rss - m[AVG_MEMORY_BYTES]) / n
        m[MAX_TPU_HBM_BYTES] = max(m[MAX_TPU_HBM_BYTES], hbm)
        m[AVG_TPU_HBM_BYTES] += (hbm - m[AVG_TPU_HBM_BYTES]) / n
        return dict(m)

    def _loop(self) -> None:
        while not self._stop.wait(self._interval_s):
            try:
                self._push(self.task_id, self.sample_once())
            except Exception:  # noqa: BLE001
                pass

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="tony-task-monitor")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
        try:
            # Final sample so short tasks (< one interval) still report real
            # numbers in their TASK_FINISHED metrics.
            self._push(self.task_id, self.sample_once())
        except Exception:  # noqa: BLE001
            pass
