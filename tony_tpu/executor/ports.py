"""Rendezvous-port reservation.

Reference model (``ServerPort.java``/``EphemeralPort.java``/``ReusablePort.java``
+ ``resources/reserve_reusable_port.py``): the executor must pick the port it
advertises to the coordinator *before* the user process exists, then hand that
port over. Two strategies:

- **Ephemeral** (default): bind port 0, read the assigned port, close before
  exec — small race window, identical to ``EphemeralPort`` semantics and the
  release-before-exec dance (``TaskExecutor.java:224-249``).
- **Reusable**: bind with SO_REUSEPORT and *keep holding* while the user
  process binds the same port with SO_REUSEPORT too — no race. The reference
  needed a Python child process to do this from Java
  (``reserve_reusable_port.py:61-89``); in-process here since the executor is
  already Python.
"""

from __future__ import annotations

import logging
import socket
from typing import Optional

log = logging.getLogger(__name__)


class ReservedPort:
    def __init__(self, reuse: bool = False):
        self.reuse = reuse
        self._sock: Optional[socket.socket] = socket.socket(
            socket.AF_INET, socket.SOCK_STREAM)
        if reuse:
            # SO_REUSEPORT is a per-platform/per-kernel nicety, and the
            # reusable strategy is an OPTIMIZATION (no release-before-exec
            # race window). Where it's missing, degrade to the ephemeral
            # strategy with a warning instead of failing the executor —
            # the reference behaves the same by only offering ReusablePort
            # where the helper works (ReusablePort.java:151-236).
            try:
                if not hasattr(socket, "SO_REUSEPORT"):
                    raise OSError(
                        "SO_REUSEPORT not supported on this platform")
                self._sock.setsockopt(socket.SOL_SOCKET,
                                      socket.SO_REUSEPORT, 1)
            except OSError as e:
                log.warning("SO_REUSEPORT unavailable (%s); falling back "
                            "to the ephemeral port strategy", e)
                self.reuse = False
        self._sock.bind(("", 0))
        self._sock.listen(1)
        self.port: int = self._sock.getsockname()[1]

    def release(self) -> None:
        """Close the holding socket. For ephemeral ports call this just before
        exec'ing the user process; for reusable ports call after the user
        process has had a chance to bind (or at executor exit)."""
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def __enter__(self) -> "ReservedPort":
        return self

    def __exit__(self, *exc) -> None:
        self.release()
