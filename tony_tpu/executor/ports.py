"""Rendezvous-port reservation.

Reference model (``ServerPort.java``/``EphemeralPort.java``/``ReusablePort.java``
+ ``resources/reserve_reusable_port.py``): the executor must pick the port it
advertises to the coordinator *before* the user process exists, then hand that
port over. Two strategies:

- **Ephemeral** (default): bind port 0, read the assigned port, close before
  exec — small race window, identical to ``EphemeralPort`` semantics and the
  release-before-exec dance (``TaskExecutor.java:224-249``).
- **Reusable**: bind with SO_REUSEPORT and *keep holding* while the user
  process binds the same port with SO_REUSEPORT too — no race. The reference
  needed a Python child process to do this from Java
  (``reserve_reusable_port.py:61-89``); in-process here since the executor is
  already Python.
"""

from __future__ import annotations

import socket
from typing import Optional


class ReservedPort:
    def __init__(self, reuse: bool = False):
        self.reuse = reuse
        self._sock: Optional[socket.socket] = socket.socket(
            socket.AF_INET, socket.SOCK_STREAM)
        if reuse:
            if not hasattr(socket, "SO_REUSEPORT"):
                raise OSError("SO_REUSEPORT not supported on this platform")
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        self._sock.bind(("", 0))
        self._sock.listen(1)
        self.port: int = self._sock.getsockname()[1]

    def release(self) -> None:
        """Close the holding socket. For ephemeral ports call this just before
        exec'ing the user process; for reusable ports call after the user
        process has had a chance to bind (or at executor exit)."""
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def __enter__(self) -> "ReservedPort":
        return self

    def __exit__(self, *exc) -> None:
        self.release()
