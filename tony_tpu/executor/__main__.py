"""`python -m tony_tpu.executor` — the per-task agent entrypoint
(reference ``TaskExecutor.main`` :211)."""

import sys

from tony_tpu.executor.executor import main

sys.exit(main())
