"""Per-task agent: registers with the coordinator, waits on the gang barrier,
wires the framework env, supervises the user process.

Reference model: ``TaskExecutor.java`` (393 LoC) — identity from env
(``initConfigs`` :255), RPC proxies to the AM (:140-145), port reservation
(:83-95), ``registerAndGetClusterSpec`` poll-until-non-null barrier
(:295-309), framework env switch (:161-207), user exec + exit-code report
(:239-243), background heartbeater (:330-370) and metrics pump (:146-150).

Fault hooks honoured: TEST_NUM_HB_MISS (skip first N heartbeats, reference
:330-357), TEST_EXECUTOR_SKEW (post-exit straggler sleep, reference :372-392).
"""

from __future__ import annotations

import json
import logging
import os
import signal
import socket
import sys
import threading
import time
from typing import Callable, Dict, Optional

from tony_tpu import constants, tracing
from tony_tpu.conf.config import TonyTpuConfig
from tony_tpu.conf import keys as K
from tony_tpu.executor.monitor import TaskMonitor
from tony_tpu.metrics import Histogram
from tony_tpu.executor.ports import ReservedPort
from tony_tpu.rpc.wire import FencedError, RpcClient
from tony_tpu.runtimes.base import TaskIdentity, get_runtime
from tony_tpu.utils import proc as procutil

log = logging.getLogger(__name__)

# The running user command's Popen, for the signal forwarder (the user
# process lives in its own session — see utils/proc.execute_shell — so a
# TERM aimed at the executor's group does not reach it on its own).
_user_proc: list = []


def _forward_signal(signum, frame) -> None:
    """Deliver the executor's TERM/INT to the user process group, with a
    KILL escalation timer, then let run() finish its teardown (monitor
    stop, result report) while the user command dies. The TERM-grace-KILL
    contract is what lets in-process checkpoint-on-preemption handlers run
    (reference grace: ApplicationMaster.java:694-711)."""
    p = _user_proc[0] if _user_proc else None
    if p is None or p.poll() is not None:
        # No user process to protect — die like a default handler would.
        raise SystemExit(128 + signum)
    log.warning("executor got signal %d; forwarding to user pgid %d",
                signum, p.pid)
    try:
        os.killpg(p.pid, signal.SIGTERM)
    except (ProcessLookupError, PermissionError):
        return
    grace = float(os.environ.get(constants.TASK_KILL_GRACE_ENV, "5") or 5)

    def _escalate():
        if p.poll() is None:
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
    t = threading.Timer(grace, _escalate)
    t.daemon = True
    t.start()


class Heartbeater(threading.Thread):
    """Reference ``TaskExecutor`` heartbeat thread :330-370, extended with
    coordinator-loss detection (crash recovery): after ``loss_threshold``
    CONSECUTIVE failed beats the thread flips to reconnect mode —
    re-resolve the coordinator, re-register the existing task identity,
    resume beating — and only if no coordinator answers within
    ``orphan_deadline_s`` does it declare the executor orphaned
    (``on_orphaned`` kills the user process: a headless gang must not
    burn TPU time forever). A FAST coordinator restart is therefore
    invisible to the user process. A FencedError at any point means a
    LIVE coordinator rejected this executor as stale (old generation or
    old session epoch) — orphaned immediately, no deadline."""

    def __init__(self, client: RpcClient, task_id: str, interval_s: float,
                 session_id: int = -1,
                 loss_threshold: int = 0,
                 reconnect: Optional[Callable[[], RpcClient]] = None,
                 orphan_deadline_s: float = 120.0,
                 on_orphaned: Optional[Callable[[str], None]] = None,
                 progress_fn: Optional[Callable[[], Optional[dict]]] = None,
                 on_dump: Optional[Callable[[], None]] = None,
                 mgen_fn: Optional[Callable[[], int]] = None,
                 on_resize: Optional[Callable[[dict], None]] = None,
                 on_profile: Optional[Callable[[dict], None]] = None):
        super().__init__(name="tony-heartbeater", daemon=True)
        self._client = client
        self._task_id = task_id
        self._session_id = session_id
        self._interval_s = interval_s
        self._loss_threshold = loss_threshold
        self._reconnect = reconnect
        self._orphan_deadline_s = orphan_deadline_s
        self._on_orphaned = on_orphaned
        # Progress beacon (coordinator/liveness.py): each beat piggybacks
        # the user process's step counter + stall age; the response may
        # carry the coordinator's dump directive for a hung verdict.
        self._progress_fn = progress_fn
        self._on_dump = on_dump
        # Elastic membership (coordinator/elastic.py): every beat carries
        # the executor's CURRENT membership generation (the topology
        # fence) and the response may carry a RESIZE directive — drain
        # (checkpoint-and-park) or release.
        self._mgen_fn = mgen_fn
        self._on_resize = on_resize
        # On-demand profiling (tony-tpu profile): the response may carry
        # a PROFILE directive — re-sent every beat until the capture
        # result rides a beacon back; the executor dedups by request id.
        self._on_profile = on_profile
        self._misses = 0
        # _stop_evt, not _stop: threading.Thread has a private _stop()
        # method; shadowing it with an Event breaks Thread.join().
        self._stop_evt = threading.Event()
        self._skip = int(os.environ.get(constants.TEST_NUM_HB_MISS, "0") or 0)

    def run(self) -> None:
        from tony_tpu import faults

        while not self._stop_evt.wait(self._interval_s):
            if self._skip > 0:
                self._skip -= 1
                log.warning("TEST hook: skipping heartbeat (%d more)",
                            self._skip)
                continue
            if faults.fire("heartbeat"):
                # Injected stall: the beat is silently dropped, exactly
                # as if the executor were wedged — the coordinator's
                # liveness monitor is what must notice.
                continue
            if faults.fire("host.loss"):
                # Sudden whole-host death: everything on the "host" dies
                # at once — the user process group AND this executor,
                # with no teardown and no exit report. The shape elastic
                # shrink-and-continue must absorb (the call counter is
                # heartbeats, so after:N places it deterministically).
                log.critical("FAULT host.loss: SIGKILLing the user "
                             "process group and hard-exiting")
                p = _user_proc[0] if _user_proc else None
                if p is not None and p.poll() is None:
                    try:
                        os.killpg(p.pid, signal.SIGKILL)
                    except (ProcessLookupError, PermissionError):
                        pass
                os._exit(137)
            progress = None
            if self._progress_fn is not None:
                try:
                    progress = self._progress_fn()
                except Exception:  # noqa: BLE001 — the beat must not die
                    progress = None
            try:
                res = self._client.call(
                    "task_executor_heartbeat",
                    task_id=self._task_id,
                    session_id=self._session_id,
                    progress=progress,
                    mgen=self._mgen_fn() if self._mgen_fn else -1)
                self._misses = 0
                if isinstance(res, dict) and res.get("dump") \
                        and self._on_dump is not None:
                    # Hung verdict: the coordinator wants all-thread
                    # stacks from the user process before it kills it.
                    try:
                        self._on_dump()
                    except Exception:  # noqa: BLE001 — best-effort
                        log.exception("stack-dump delivery failed")
                if isinstance(res, dict) \
                        and isinstance(res.get("resize"), dict) \
                        and self._on_resize is not None:
                    try:
                        self._on_resize(res["resize"])
                    except Exception:  # noqa: BLE001 — keep beating
                        log.exception("resize directive handling failed")
                if isinstance(res, dict) \
                        and isinstance(res.get("profile"), dict) \
                        and self._on_profile is not None:
                    try:
                        self._on_profile(res["profile"])
                    except Exception:  # noqa: BLE001 — keep beating
                        log.exception("profile directive handling failed")
            except FencedError as e:
                self._orphan(f"fenced by a live coordinator: {e}")
                return
            except Exception as e:  # noqa: BLE001
                self._misses += 1
                log.warning("heartbeat failed (%d consecutive): %s",
                            self._misses, e)
                if self._loss_threshold and self._reconnect is not None \
                        and self._misses >= self._loss_threshold:
                    if not self._reenter():
                        return

    def _reenter(self) -> bool:
        """Coordinator-loss mode: keep trying to re-resolve + re-register
        until success, normal stop, fencing, or the orphan deadline."""
        log.error("coordinator unreachable after %d heartbeats — entering "
                  "reconnect mode (orphan deadline %.0fs)",
                  self._misses, self._orphan_deadline_s)
        deadline = time.monotonic() + self._orphan_deadline_s
        while not self._stop_evt.is_set():
            try:
                self._client = self._reconnect()
                self._misses = 0
                log.warning("re-registered %s with the coordinator; "
                            "resuming heartbeats", self._task_id)
                return True
            except FencedError as e:
                self._orphan(f"fenced during re-registration: {e}")
                return False
            except Exception as e:  # noqa: BLE001
                log.warning("re-registration attempt failed: %s", e)
            if time.monotonic() >= deadline:
                self._orphan(
                    f"no coordinator within the {self._orphan_deadline_s:.0f}s"
                    f" orphan deadline")
                return False
            if self._stop_evt.wait(min(self._interval_s, 2.0)):
                return False       # normal stop while reconnecting
        return False

    def _orphan(self, reason: str) -> None:
        if self._on_orphaned is not None and not self._stop_evt.is_set():
            self._on_orphaned(reason)

    def stop(self) -> None:
        self._stop_evt.set()


class TaskExecutor:
    def __init__(self, env: Optional[Dict[str, str]] = None):
        e = env or os.environ
        self.job_name = e[constants.JOB_NAME]
        self.index = int(e[constants.TASK_INDEX])
        self.task_num = int(e[constants.TASK_NUM])
        self.is_chief = e.get(constants.IS_CHIEF, "false") == "true"
        self.session_id = int(e.get(constants.SESSION_ID, "0"))
        self.task_id = e.get(constants.TASK_ID,
                             f"{self.job_name}:{self.index}")
        self.coordinator_host = e[constants.COORDINATOR_HOST]
        self.coordinator_port = int(e[constants.COORDINATOR_PORT])
        self.command = e.get(constants.TASK_COMMAND, "")
        conf_path = e.get(constants.EXECUTOR_CONF, "")
        from tony_tpu.storage.store import is_url
        if conf_path and is_url(conf_path):
            # Frozen config lives in the remote store (multi-host path);
            # fetch it with the env credential before reading any key.
            from tony_tpu.storage import get_store

            local = os.path.join(os.getcwd(), constants.FINAL_CONFIG_FILE)
            get_store(conf_path).get_file(conf_path, local)
            conf_path = local
        self.conf = (TonyTpuConfig.load_final(conf_path)
                     if conf_path and os.path.exists(conf_path)
                     else TonyTpuConfig())
        tls = None
        tls_cert = str(self.conf.get(K.SECURITY_TLS_CERT, "") or "")
        if tls_cert:
            from tony_tpu.rpc.wire import client_tls_context
            tls = client_tls_context(tls_cert)
        self._rpc_token = e.get("TONY_RPC_TOKEN") or None
        self._tls = tls
        # Crash-recovery contract: the launch-time coordinator generation
        # fences every frame (adopted upward on reconnect, stale rejected),
        # and the address file is how a RESTARTED coordinator — fresh
        # ephemeral port — is re-resolved.
        self.generation = int(
            e.get(constants.COORDINATOR_GENERATION, "0") or 0)
        self.coordinator_addr_file = e.get(constants.COORDINATOR_ADDR_FILE,
                                           "")
        # Elastic membership generation (coordinator/elastic.py): -1 =
        # not an elastic job (compat-accepted by the coordinator).
        # Survivors adopt newer generations from the RESIZE directive
        # riding the heartbeat response; a frame carrying a stale value
        # with no resize in flight is fenced.
        try:
            self.mgen = int(e.get(constants.MEMBERSHIP_GEN, "") or -1)
        except ValueError:
            self.mgen = -1
        self._resize_lock = threading.Lock()
        self._resize_directive: Optional[dict] = None
        self._released = False
        # On-demand profiling: request ids already written to the user
        # process's request file (the directive re-rides every beat until
        # the result lands — write each request exactly once).
        self._profile_ids: set = set()
        self._rpc_max_retries = self.conf.get_int(K.RPC_MAX_RETRIES, 10)
        self._rpc_retry_sleep_s = float(
            self.conf.get(K.RPC_RETRY_SLEEP_S, 2.0) or 2.0)
        # Per-call deadline so a WEDGED coordinator can't park the
        # heartbeat thread forever (the precondition for loss detection).
        self._rpc_call_timeout_s = float(
            self.conf.get(K.RPC_CALL_TIMEOUT_S, 10.0) or 0) or None
        # Client-side RPC latency histogram: cumulative over this
        # executor's lifetime, shipped on every heartbeat beacon and
        # re-exposed by the coordinator as tony_rpc_client_seconds.
        self._rpc_hist = Histogram()
        # Distributed tracing (tony_tpu/tracing.py): the coordinator
        # exported the job's trace id and this task's lifecycle span as
        # our parent; spans are buffered locally and shipped home over
        # trace.push. Absent env (tracing off / old coordinator) = no-op.
        self.tracer = tracing.Tracer(
            trace_id=e.get(constants.TRACE_ID_ENV) or None,
            service=f"executor:{self.task_id}",
            enabled=bool(e.get(constants.TRACE_ID_ENV)))
        self._trace_parent = e.get(constants.TRACE_PARENT_ENV, "")
        self._run_span = tracing.NULL_SPAN
        self._trace_ctx: Optional[tuple] = None
        self._user_start_us = 0
        self._first_step_emitted = False
        self._monitor: Optional[TaskMonitor] = None
        self.client = self._make_client(self.coordinator_host,
                                        self.coordinator_port)
        self._orphaned_reason: Optional[str] = None
        # Progress beacon state (coordinator/liveness.py): the executor
        # tails the user process's telemetry file and reports the step
        # counter plus how long ago IT last saw the counter move — a
        # duration, so coordinator/executor clock skew never corrupts the
        # stall measurement.
        self._metrics_file = ""
        self._beacon_steps: Optional[float] = None
        self._beacon_advance_t = 0.0
        # Signal delivered to the user process group on a hung verdict;
        # `import tony_tpu` in the user process pre-registers a
        # faulthandler all-thread dump on it. Operators can move it via
        # the TONY_STACKDUMP_SIGNAL env (execution-env passthrough).
        try:
            self._dump_signal = int(
                e.get(constants.STACKDUMP_SIGNAL, "") or 0) \
                or int(signal.SIGUSR1)
        except ValueError:
            self._dump_signal = int(signal.SIGUSR1)
        # Warm-pool adoption marker (tony_tpu/pool.py): stamped into the
        # lease env by the pool daemon; empty on cold-spawned executors.
        # Drives the adopted=true span attributes — nothing else differs:
        # an adopted executor is indistinguishable to the coordinator.
        self._pool_worker = e.get(constants.POOL_WORKER_ID, "")
        self.hostname = e.get("TONY_ADVERTISED_HOST") or socket.gethostname()
        try:
            socket.getaddrinfo(self.hostname, None)
        except OSError:
            self.hostname = "127.0.0.1"
        self.rendezvous_port: Optional[ReservedPort] = None
        self.tb_port: Optional[ReservedPort] = None

    # -- coordinator link (crash recovery) -------------------------------
    def _make_client(self, host: str, port: int) -> RpcClient:
        client = RpcClient(
            host, port, token=self._rpc_token,
            max_retries=self._rpc_max_retries,
            retry_sleep_s=self._rpc_retry_sleep_s,
            tls=self._tls, generation=self.generation,
            call_timeout_s=self._rpc_call_timeout_s,
            on_latency=self._record_rpc_latency, peer="coordinator")
        client.trace_context = self._trace_ctx
        return client

    def _record_rpc_latency(self, method: str, seconds: float) -> None:
        self._rpc_hist.observe(seconds)

    def _flush_trace(self) -> None:
        """Ship buffered spans to the coordinator's span log. Best-effort:
        spans are only ever shipped COMPLETE, so a failed push loses
        detail but can never leave the job's trace with an unclosed
        executor span."""
        if not self.tracer.enabled:
            return
        records = self.tracer.drain()
        if not records:
            return
        try:
            self.client.call("trace.push", records=records)
        except Exception as e:  # noqa: BLE001 — tracing is best-effort
            log.debug("trace push failed (%d spans dropped): %s",
                      len(records), e)

    def _resolve_coordinator(self) -> None:
        """Re-read the coordinator address file, if one is reachable from
        this host: a recovered coordinator binds a fresh ephemeral port
        and rewrites the file. Unreadable/absent → keep the last known
        address (a coordinator restarted on a fixed host:port needs no
        file)."""
        if not self.coordinator_addr_file:
            return
        try:
            with open(self.coordinator_addr_file, encoding="utf-8") as f:
                addr = json.load(f)
            self.coordinator_host = addr["host"]
            self.coordinator_port = int(addr["port"])
            self._rpc_token = addr.get("token") or None
        except (OSError, ValueError, KeyError) as e:
            log.debug("could not re-resolve coordinator from %s: %s",
                      self.coordinator_addr_file, e)

    def _reconnect_coordinator(self) -> RpcClient:
        """One reconnect attempt for the Heartbeater's loss mode:
        re-resolve the address, dial with a SHORT budget (the outer loop
        owns pacing), and re-register the existing task identity so the
        recovered coordinator re-adopts this task without touching the
        user process. Raises on failure; FencedError means a live
        coordinator ruled this executor stale — terminal."""
        from tony_tpu import faults

        faults.check("executor.reregister")
        self._resolve_coordinator()
        client = RpcClient(
            self.coordinator_host, self.coordinator_port,
            token=self._rpc_token, max_retries=1, retry_sleep_s=0.1,
            connect_timeout_s=5.0, tls=self._tls,
            generation=self.generation,
            call_timeout_s=self._rpc_call_timeout_s,
            on_latency=self._record_rpc_latency, peer="coordinator")
        client.trace_context = self._trace_ctx
        try:
            client.call("register_worker_spec", task_id=self.task_id,
                        host=self.hostname,
                        port=self.rendezvous_port.port
                        if self.rendezvous_port else 0,
                        session_id=self.session_id, mgen=self.mgen)
        except BaseException:
            client.close()
            raise
        # Adopt the successor's generation for all future frames.
        self.generation = max(self.generation, client.generation)
        old, self.client = self.client, client
        old.close()
        return client

    # -- progress liveness + metrics beacon ------------------------------
    def _progress_beacon(self) -> Optional[dict]:
        """Heartbeat payload, two audiences in one dict. For the liveness
        tracker (coordinator/liveness.py): the user process's step counter
        (published by telemetry.step() into the metrics file) plus the age
        of its last advance as seen from THIS process — absent while the
        task has no progress instrumentation, so the coordinator keeps it
        on heartbeat-only liveness (one-time warning, never a false kill).
        Any counter CHANGE counts as an advance ('!=' not '>': a user
        process restarted inside the same task resets the counter downward
        and is very much alive). For the live-metrics registry: a
        ``metrics`` sub-dict (steps/s, MFU, HBM, RSS) and the cumulative
        RPC client-latency histogram snapshot."""
        if not self._metrics_file:
            return None
        from tony_tpu import telemetry

        stats = telemetry.read_stats(self._metrics_file)
        beacon: Dict[str, object] = {}
        steps = stats.get("steps_completed")
        if steps is not None:
            now = time.monotonic()
            steps = float(steps)
            if self._beacon_steps is None or steps != self._beacon_steps:
                self._beacon_steps = steps
                self._beacon_advance_t = now
            beacon["steps"] = steps
            beacon["age_s"] = round(now - self._beacon_advance_t, 3)
            self._maybe_emit_first_step(stats, steps)
        m: Dict[str, float] = {}
        for src, dst in (("steps_per_sec", "steps_per_sec"),
                         ("tokens_per_sec", "tokens_per_sec"),
                         ("mfu_vs_peak_bf16", "mfu"),
                         ("hbm_bytes_in_use", "hbm_bytes")):
            v = stats.get(src)
            if isinstance(v, (int, float)):
                m[dst] = float(v)
        if self._monitor is not None and self._monitor.last_rss:
            m["rss_bytes"] = self._monitor.last_rss
        if m:
            beacon["metrics"] = m
        ph = stats.get("step_phases")
        if isinstance(ph, dict) and ph:
            # Step-time attribution: cumulative per-phase seconds + the
            # recent ring means → tony_step_phase_seconds gauges and the
            # `top` phase bar (tony_tpu/profiling/).
            beacon["phases"] = ph
        prof = stats.get("profile")
        if isinstance(prof, dict) and prof:
            # On-demand capture status/result — the coordinator matches
            # it to its request by id and emits TASK_PROFILED.
            beacon["profile"] = prof
        if self._rpc_hist.count:
            beacon["rpc"] = self._rpc_hist.snapshot()
        return beacon or None

    def _maybe_emit_first_step(self, stats: dict, steps: float) -> None:
        """Record the submit→first-step tail: a complete span from user-
        process start to the FIRST telemetry step, end-anchored on the
        user process's own wall timestamp (telemetry first_step_done_ts)
        rather than this poll's arrival time. The span bench.py measures
        its submit_to_first_step_s from."""
        if self._first_step_emitted or steps < 1 \
                or not self.tracer.enabled or not self._user_start_us:
            return
        self._first_step_emitted = True
        end_ts = stats.get("first_step_done_ts")
        try:
            end_us = int(float(end_ts) * 1e6) if end_ts else tracing.now_us()
        except (TypeError, ValueError):
            end_us = tracing.now_us()
        self.tracer.emit("executor.first_step",
                         start_us=self._user_start_us,
                         end_us=max(end_us, self._user_start_us),
                         parent=self._run_span, task=self.task_id,
                         attrs={"steps_at_observation": steps})

    def _dump_user_stacks(self) -> None:
        """Coordinator declared this task HUNG: deliver the dump signal so
        the pre-registered faulthandler handler writes all-thread stacks
        into the task log — the diagnostics pass before the
        TERM-grace-KILL lands. The target is the PID stamped into the
        metrics file: exactly the process whose step counter froze, and
        by construction one that imported tony_tpu (so the handler is
        registered). Blasting the whole group instead would kill any
        member WITHOUT a handler — the `/bin/sh -c` wrapper dies on an
        unhandled SIGUSR1 and turns the diagnostics pass into the kill."""
        p = _user_proc[0] if _user_proc else None
        if p is None or p.poll() is not None:
            log.warning("coordinator requested a stack dump but no user "
                        "process is running")
            return
        from tony_tpu import telemetry

        pid = 0
        try:
            pid = int(telemetry.read_stats(self._metrics_file).get("pid", 0))
        except (TypeError, ValueError):
            pid = 0
        try:
            # Guard against pid recycling: only signal a pid still inside
            # the user command's process group.
            if not pid or os.getpgid(pid) != p.pid:
                log.warning("no live instrumented pid to stack-dump "
                            "(metrics pid %s outside user pgid %d)",
                            pid or "?", p.pid)
                return
            log.warning("coordinator declared %s hung; sending dump "
                        "signal %d to instrumented pid %d for an "
                        "all-thread stack dump",
                        self.task_id, self._dump_signal, pid)
            os.kill(pid, self._dump_signal)
        except (ProcessLookupError, PermissionError) as e:
            log.warning("stack-dump signal failed: %s", e)

    # -- on-demand profiling (tony-tpu profile) --------------------------
    def _profile_request_path(self) -> str:
        return os.path.join(os.getcwd(), constants.PROFILE_REQUEST_FILE)

    def _on_profile_directive(self, directive: dict) -> None:
        """PROFILE directive off the heartbeat response (the dump/RESIZE
        pattern): hand the request to the user process by writing the
        request file its telemetry reporter polls
        (TONY_PROFILE_REQUEST_FILE). Deduped by request id — the
        coordinator re-sends the directive every beat until the capture
        result rides a beacon back; the file is written exactly once per
        request. Atomic replace: the reporter must never adopt a torn
        request (it would dedup a garbage id)."""
        try:
            req_id = int(directive.get("id", 0))
        except (TypeError, ValueError):
            return
        if req_id <= 0 or req_id in self._profile_ids:
            return
        self._profile_ids.add(req_id)
        from tony_tpu.utils.durable import atomic_write

        try:
            atomic_write(self._profile_request_path(),
                         json.dumps(directive).encode("utf-8"))
            log.info("profile request %d (steps=%s) written for the "
                     "user process", req_id, directive.get("steps"))
        except OSError as e:
            log.warning("could not write profile request %d: %s",
                        req_id, e)

    # -- elastic resize (coordinator/elastic.py) -------------------------
    def _on_resize(self, directive: dict) -> None:
        """RESIZE directive off the heartbeat response (the dump-
        directive pattern): the gang is re-meshing. Drain the user
        process at a step barrier — TERM so its save-on-SIGTERM handler
        makes one final durable save, KILL after the drain grace — and
        leave the park/release decision to the run loop once the exit
        lands. Re-sent every beat while the drain runs; dedup on the
        membership generation (never act twice, never act on a stale
        generation after adopting a newer one)."""
        try:
            mgen = int(directive.get("mgen", -1))
        except (TypeError, ValueError):
            return
        with self._resize_lock:
            cur = self._resize_directive
            if mgen <= self.mgen or (
                    cur is not None and mgen <= int(cur.get("mgen", -1))):
                return
            self._resize_directive = dict(directive)
        action = str(directive.get("action", "drain"))
        log.warning("resize directive: %s under membership generation "
                    "%d (size %s) — draining the user process",
                    action, mgen, directive.get("size"))
        p = _user_proc[0] if _user_proc else None
        if p is None or p.poll() is not None:
            return                 # nothing to drain; the loop handles it
        try:
            os.killpg(p.pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            return
        try:
            grace = float(directive.get("grace_s") or 0) or float(
                os.environ.get(constants.TASK_KILL_GRACE_ENV, "15") or 15)
        except (TypeError, ValueError):
            grace = 15.0

        def _escalate():
            if p.poll() is None:
                log.warning("resize drain grace (%.0fs) expired; "
                            "SIGKILLing the user process group", grace)
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
        timer = threading.Timer(grace, _escalate)
        timer.daemon = True
        timer.start()

    def _take_resize_directive(self) -> Optional[dict]:
        """Consume the pending directive (run loop, after a user-process
        exit): adopting the new membership generation here makes every
        later frame — heartbeats, the park re-registration — carry it."""
        with self._resize_lock:
            d, self._resize_directive = self._resize_directive, None
        if d is not None:
            self.mgen = max(self.mgen, int(d.get("mgen", -1)))
        return d

    def _gang_position(self, cluster_spec) -> tuple:
        """(dense_rank, world, members) for this task under the spec's
        elastic metadata. A post-shrink gang keeps SURVIVOR indices —
        task identity is stable — so the wire spec lists members in
        dense-rank order and this maps our stable index into it. Plain
        (index, task_num, range) for non-elastic jobs."""
        meta = cluster_spec.pop("__elastic__", None) \
            if isinstance(cluster_spec, dict) else None
        members = None
        if isinstance(meta, dict):
            try:
                self.mgen = max(self.mgen, int(meta.get("mgen", -1)))
            except (TypeError, ValueError):
                pass
            raw = (meta.get("members") or {}).get(self.job_name)
            if raw:
                members = sorted(int(m) for m in raw)
        if members and self.index in members:
            return members.index(self.index), len(members), members
        return self.index, self.task_num, list(range(self.task_num))

    def _orphan_teardown(self, reason: str) -> None:
        """No coordinator will ever hear from us again (deadline expired)
        or a live one fenced us out as stale: deliver the TERM-grace-KILL
        ladder to the user process group and let run() unwind. Without
        this, a lost coordinator leaves headless executors training into
        the void indefinitely."""
        self._orphaned_reason = reason
        log.error("executor orphaned (%s); stopping user process", reason)
        p = _user_proc[0] if _user_proc else None
        if p is not None and p.poll() is None:
            grace = float(os.environ.get(constants.TASK_KILL_GRACE_ENV,
                                         "5") or 5)
            procutil.kill_process_groups([p.pid], grace_s=grace)

    # -- setup ----------------------------------------------------------
    def setup_ports(self) -> None:
        """Reserve the rendezvous port (+ TensorBoard port if chief);
        reference ``TaskExecutor.setupPorts`` :83-95."""
        reuse = self.conf.get_bool(K.TASK_REUSE_PORT) or \
            os.environ.get("TF_GRPC_REUSE_PORT", "").lower() == "true"
        # Missing SO_REUSEPORT degrades to the ephemeral strategy inside
        # ReservedPort itself (with a warning), so no fallback here.
        self.rendezvous_port = ReservedPort(reuse=reuse)
        if self.is_chief:
            self.tb_port = ReservedPort(reuse=False)
            try:
                self.client.call(
                    "register_tensorboard_url", task_id=self.task_id,
                    url=f"http://{self.hostname}:{self.tb_port.port}",
                    session_id=self.session_id)
            except Exception as e:  # noqa: BLE001
                log.warning("TB registration failed: %s", e)
        port_file = str(self.conf.get(K.TASK_PORT_FILE, "") or "")
        if port_file:
            with open(port_file, "w") as f:
                f.write(str(self.rendezvous_port.port))

    def register_and_get_cluster_spec(self) -> Optional[dict]:
        """The gang barrier (reference :295-309): re-register every 3 s until
        the coordinator returns the complete spec."""
        timeout_s = self.conf.get_int(K.TASK_REGISTRATION_TIMEOUT_S, 900)
        if os.environ.get(constants.TEST_SKIP_REGISTRATION):
            # Simulates an executor that never reaches the coordinator so the
            # coordinator-side registration timeout can be exercised E2E
            # (reference kills stuck allocations after the timeout,
            # ``ApplicationMaster.java:791-888``).
            log.warning("TEST hook: skipping registration; sleeping")
            # Outlive the coordinator's registration timeout but stay
            # bounded: an unbounded multiple of a production-sized timeout
            # left zombie sleepers wedging suite teardown (VERDICT r3 #7).
            time.sleep(min(timeout_s * 4, 120))
            return None

        def attempt() -> Optional[dict]:
            try:
                return self.client.call(
                    "register_worker_spec", task_id=self.task_id,
                    host=self.hostname, port=self.rendezvous_port.port,
                    session_id=self.session_id, mgen=self.mgen)
            except FencedError:
                # A live coordinator ruled this executor stale (old
                # generation/epoch): polling cannot fix that — abort.
                raise
            except Exception as e:  # noqa: BLE001
                log.warning("register_worker_spec failed: %s", e)
                return None

        return procutil.poll_till_non_null(
            attempt, interval_s=0.3, timeout_s=timeout_s)

    def _park_ack_for_migration(self) -> bool:
        """Deliver ONE park acknowledgement for a live migration, then
        return — never wait for the spec. Survives a coordinator outage
        the same way a result report does (the mid-migration SIGKILL
        drill): re-resolve + retry inside the orphan deadline, so the
        RECOVERED coordinator re-entering the journaled move collects
        this ack. FencedError is terminal (a live coordinator already
        moved past this incarnation); an exhausted deadline just exits —
        the coordinator's drain degrades to the heartbeat-expiry ladder."""
        deadline = time.monotonic() + float(
            self.conf.get_int(K.TASK_ORPHAN_DEADLINE_S, 120))
        while True:
            try:
                self.client.call(
                    "register_worker_spec", task_id=self.task_id,
                    host=self.hostname, port=self.rendezvous_port.port,
                    session_id=self.session_id, mgen=self.mgen)
                return True
            except FencedError as e:
                log.warning("migration park ack for %s fenced: %s",
                            self.task_id, e)
                return False
            except Exception as e:  # noqa: BLE001
                if time.monotonic() >= deadline:
                    log.warning("migration park ack failed within the "
                                "orphan deadline: %s", e)
                    return False
                log.info("migration park ack failed (%s); re-resolving "
                         "the coordinator and retrying", e)
                time.sleep(0.5)
                self._resolve_coordinator()
                old, self.client = self.client, self._make_client(
                    self.coordinator_host, self.coordinator_port)
                old.close()

    def _localize_bundle(self) -> None:
        """Localize the staged job bundle, container resources, and venv
        into this task's working dir (reference ``Utils.extractResources``
        :710-723 unzipping the HDFS-localized src/venv archives, and YARN
        resource localization per ``LocalizableResource``).

        Cold-start posture: runs in a BACKGROUND thread overlapped with
        port setup + the registration barrier (run() joins it before the
        user process launches), fetches resources concurrently, and skips
        content-unchanged files via the workdir manifest
        (utils/localize.py) — a retry epoch re-localizing into the same
        task dir pays ~nothing."""
        from tony_tpu.storage.store import is_url
        from tony_tpu.utils import localize as loc

        workdir = os.getcwd()
        manifest = loc.load_manifest(workdir)
        bundle = str(self.conf.get(K.INTERNAL_BUNDLE_DIR, "") or "")
        if bundle and is_url(bundle):
            from tony_tpu.storage import get_store

            get_store(bundle).get_tree(bundle, workdir)
        elif bundle and os.path.isdir(bundle):
            import shutil

            sig = f"__bundle__|{loc.tree_signature(bundle)}"
            if manifest.get("__bundle__") != sig:
                shutil.copytree(bundle, workdir, dirs_exist_ok=True)
                manifest["__bundle__"] = sig
            else:
                log.debug("bundle localization skip (content unchanged)")
        resources = self.conf.get_list(K.INTERNAL_RESOURCES)
        if resources:
            loc.localize_resources(resources, workdir, manifest=manifest)
        venv = str(self.conf.get(K.INTERNAL_VENV, "") or "")
        if venv and is_url(venv):
            from tony_tpu.storage import get_store

            local = os.path.join(workdir, os.path.basename(venv))
            get_store(venv).get_file(venv, local)
            venv = local
        if venv and os.path.isfile(venv):
            import shutil

            venv_sig = f"__venv__|{loc.file_content_hash(venv)}"
            venv_dir = os.path.join(workdir, "venv")
            if manifest.get("__venv__") == venv_sig \
                    and os.path.isdir(venv_dir):
                log.debug("venv localization skip (content unchanged)")
            else:
                os.makedirs(venv_dir, exist_ok=True)
                shutil.unpack_archive(venv, venv_dir)
                manifest["__venv__"] = venv_sig
                # Archived venvs lose the executable bit on their binaries
                # when zipped; restore it so venv/bin/python is runnable.
                bin_dir = os.path.join(venv_dir, "bin")
                if os.path.isdir(bin_dir):
                    for f in os.listdir(bin_dir):
                        p = os.path.join(bin_dir, f)
                        if os.path.isfile(p):
                            os.chmod(p, os.stat(p).st_mode | 0o755)
        loc.save_manifest(workdir, manifest)

    # -- run ------------------------------------------------------------
    def run(self) -> int:
        if not self.command:
            log.error("no task command configured for %s", self.task_id)
            return constants.EXIT_FAILURE
        # Postmortem span durability: the buffered complete-only sink
        # only reaches the job's span log via trace.push, so an executor
        # dying on SIGTERM (backend kill, preemption ladder) used to
        # take its whole side of the timeline with it. atexit covers
        # every orderly-ish death — the signal forwarder exits via
        # SystemExit, which runs atexit hooks; only SIGKILL still loses
        # the buffer (and can lose nothing else either).
        import atexit
        atexit.register(self._flush_trace)
        self._run_span = self.tracer.start_span(
            "executor.run", parent=self._trace_parent, task=self.task_id,
            attrs={"pooled": self._pool_worker} if self._pool_worker
            else None)
        # Every RPC this executor makes carries the trace context, so
        # coordinator-side RPC spans stitch under this run span.
        self._trace_ctx = (self.tracer.trace_id, self._run_span.span_id) \
            if self.tracer.enabled else None
        self.client.trace_context = self._trace_ctx
        # Localization overlaps the registration barrier: the staged
        # bytes only need to be in place before the USER process starts,
        # and the gang barrier routinely idles for seconds waiting on
        # peers — run() joins this thread (and re-raises its failure)
        # right after the barrier opens, before the runtime env is built.
        localize_span = self.tracer.start_span(
            "executor.localize", parent=self._run_span, task=self.task_id)
        localize_err: list = []

        def _localize_bg() -> None:
            try:
                self._localize_bundle()
            except BaseException as e:  # noqa: BLE001 — re-raised at join
                localize_err.append(e)
            finally:
                localize_span.end(error=str(localize_err[0])[:200]
                                  if localize_err else "")

        localize_thread = threading.Thread(
            target=_localize_bg, name="tony-localize", daemon=True)
        localize_thread.start()
        self.setup_ports()
        metrics_file = os.path.join(os.getcwd(), "user-metrics.json")
        self._metrics_file = metrics_file
        hb = Heartbeater(
            self.client, self.task_id,
            self.conf.get_int(K.TASK_HEARTBEAT_INTERVAL_MS, 1000) / 1000.0,
            session_id=self.session_id,
            loss_threshold=self.conf.get_int(
                K.TASK_COORDINATOR_LOSS_HEARTBEATS, 3),
            reconnect=self._reconnect_coordinator,
            orphan_deadline_s=float(
                self.conf.get_int(K.TASK_ORPHAN_DEADLINE_S, 120)),
            on_orphaned=self._orphan_teardown,
            progress_fn=self._progress_beacon,
            on_dump=self._dump_user_stacks,
            mgen_fn=lambda: self.mgen,
            on_resize=self._on_resize,
            on_profile=self._on_profile_directive)
        hb.start()
        monitor = TaskMonitor(
            self.task_id,
            push=lambda tid, m: self.client.call("metrics.push", task_id=tid,
                                                 metrics=m),
            interval_s=self.conf.get_int(K.TASK_METRICS_INTERVAL_MS,
                                         5000) / 1000.0,
            metrics_file=metrics_file)

        register_span = self.tracer.start_span(
            "executor.register", parent=self._run_span, task=self.task_id,
            attrs={"adopted": True, "pool_worker": self._pool_worker}
            if self._pool_worker else None)
        try:
            cluster_spec = self.register_and_get_cluster_spec()
        except FencedError as e:
            register_span.end(fenced=True)
            log.error("registration fenced for %s: %s", self.task_id, e)
            return constants.EXIT_KILLED
        register_span.end(barrier_open=cluster_spec is not None)
        if cluster_spec is None:
            log.error("registration barrier timed out for %s", self.task_id)
            self._run_span.end(barrier_timeout=True)
            self._flush_trace()
            return constants.EXIT_FAILURE
        log.info("cluster spec: %s", cluster_spec)
        # The barrier is open; the staged bytes must now actually be in
        # place (and a localization failure must fail THIS task the same
        # way it did when localization ran serially before registration).
        localize_thread.join()
        if localize_err:
            hb.stop()
            log.error("bundle localization failed for %s: %s",
                      self.task_id, localize_err[0])
            self._run_span.end(localize_error=str(localize_err[0])[:200])
            self._flush_trace()
            return constants.EXIT_FAILURE
        # First flush: registration/localization spans reach the span log
        # even if this executor is later SIGKILLed mid-training.
        self._flush_trace()

        framework = str(self.conf.get(K.APPLICATION_FRAMEWORK, "jax"))
        runtime = get_runtime(framework)

        def _on_user_start(p) -> None:
            # Publish the user pgid: in-process for the signal forwarder,
            # on disk for backends that must reap the user tree even after
            # this executor is SIGKILLed (constants.USER_PGID_FILE).
            self._user_start_us = tracing.now_us()
            _user_proc[:] = [p]
            try:
                with open(os.path.join(os.getcwd(),
                                       constants.USER_PGID_FILE), "w") as f:
                    f.write(str(p.pid))
            except OSError as e:
                log.warning("could not write %s: %s",
                            constants.USER_PGID_FILE, e)

        # Root the proc-tree walk at the executor itself: the user process
        # is a descendant, and this root stays sampleable after the child
        # exits (a dead child pid would zero the final sample short tasks
        # rely on). Started ONCE — it spans elastic park/relaunch cycles.
        monitor._pid_fn = os.getpid
        monitor.start()
        self._monitor = monitor

        # Spot/preemptible TPU VMs: the metadata server's advance notice
        # becomes a SIGTERM to the user group, so save-on-preemption
        # handlers run inside the warning window (executor/preemption.py;
        # silently off when no metadata server answers).
        from tony_tpu.executor.preemption import start_for_executor
        preempt_watcher = start_for_executor(_user_proc)

        tb_proc = None
        ports_released = False
        exit_code = constants.EXIT_FAILURE
        try:
            # The user process runs inside a loop because of elastic
            # resizes (coordinator/elastic.py): a drained survivor PARKS
            # — re-registers its existing identity under the new
            # membership generation, waits at the barrier, and relaunches
            # the user command at the new world size — instead of
            # reporting an exit. Exactly one iteration for non-elastic
            # jobs (the common case breaks at the bottom).
            while True:
                rank, world, members = self._gang_position(cluster_spec)
                me = TaskIdentity(self.job_name, rank, world,
                                  self.is_chief,
                                  self.rendezvous_port.port)
                env = runtime.build_env(cluster_spec, me, self.conf)
                # Reference-compat aliases: user scripts written against
                # the reference read bare names (Constants.java:104-110 —
                # JOB_NAME/TASK_INDEX/... without the TONY_ prefix).
                # TASK_INDEX/TASK_NUM are the DENSE rank and world: after
                # a shrink the member indices are sparse, and what user
                # data pipelines need is their position in the gang.
                env.update({
                    "JOB_NAME": self.job_name,
                    "TASK_INDEX": str(rank),
                    "TASK_NUM": str(world),
                    "IS_CHIEF": "true" if self.is_chief else "false",
                    "SESSION_ID": str(self.session_id),
                })
                env[constants.GANG_MEMBERS] = ",".join(
                    str(m) for m in members)
                if self.mgen >= 0:
                    env[constants.MEMBERSHIP_GEN] = str(self.mgen)
                if self.tb_port is not None:
                    env[constants.TB_PORT] = str(self.tb_port.port)
                # The user process reports its own device stats here (it
                # owns the chips; see tony_tpu/telemetry.py) and the
                # monitor tails the file.
                env[constants.METRICS_FILE] = metrics_file
                # On-demand profiling request channel: the telemetry
                # reporter polls this file for PROFILE directives the
                # executor writes off the heartbeat response.
                env[constants.PROFILE_REQUEST_ENV] = \
                    self._profile_request_path()
                # Hung-task diagnostics contract: `import tony_tpu` in
                # the user process pre-registers a faulthandler
                # all-thread stack dump on this signal; _dump_user_stacks
                # delivers it on the coordinator's hung verdict.
                env.setdefault(constants.STACKDUMP_SIGNAL,
                               str(self._dump_signal))
                if tb_proc is None:
                    tb_proc = self._maybe_launch_tensorboard(env)
                if not ports_released:
                    # Release-before-exec dance (reference :224-249):
                    # ephemeral ports must be free for the user process
                    # to bind; reusable ports stay held.
                    if not self.rendezvous_port.reuse:
                        self.rendezvous_port.release()
                    if self.tb_port is not None:
                        self.tb_port.release()
                    ports_released = True
                user_span = self.tracer.start_span(
                    "executor.user_process", parent=self._run_span,
                    task=self.task_id,
                    attrs={"world": world, "rank": rank})
                try:
                    exit_code = procutil.execute_shell(
                        self.command,
                        timeout_s=self.conf.get_int(
                            K.TASK_EXECUTOR_EXECUTION_TIMEOUT_S, 0),
                        env=env, on_start=_on_user_start)
                    user_span.end(exit_code=exit_code)
                finally:
                    user_span.end(aborted=True)   # no-op when ended above
                    _user_proc[:] = []
                    # The group is reaped (execute_shell's finally); drop
                    # the pgid file so later backend kills can't TERM a
                    # recycled group id while the executor lingers
                    # through reporting/teardown (ADVICE r4: same-user
                    # pgid reuse isn't caught by the PermissionError
                    # guard).
                    try:
                        os.unlink(os.path.join(os.getcwd(),
                                               constants.USER_PGID_FILE))
                    except OSError:
                        pass
                log.info("user process for %s exited with %d",
                         self.task_id, exit_code)
                directive = self._take_resize_directive()
                if directive is None or self._orphaned_reason is not None:
                    break
                if str(directive.get("action")) == "release":
                    # Shrunk out of the gang: no coordinator wants this
                    # exit — the re-meshed topology no longer holds the
                    # task (a result report would be fenced anyway).
                    self._released = True
                    break
                if directive.get("migrate"):
                    # Live migration: the gang relaunches on the
                    # DESTINATION slice under this same task identity.
                    # Waiting at the barrier would hand THIS incarnation
                    # the re-meshed spec meant for its replacement — two
                    # gangs training at once — so ack the park (the
                    # coordinator's drain completes on it) and exit with
                    # the quiet released shape.
                    log.warning("migrating to %r under membership "
                                "generation %d: acking the drain and "
                                "exiting %s", directive.get("target"),
                                self.mgen, self.task_id)
                    park_span = self.tracer.start_span(
                        "executor.park", parent=self._run_span,
                        task=self.task_id,
                        attrs={"mgen": self.mgen, "migrate": True})
                    acked = self._park_ack_for_migration()
                    park_span.end(acked=acked)
                    self._released = True
                    break
                # PARK: re-register the existing identity under the new
                # membership generation and wait at the barrier for the
                # re-meshed spec — the user process relaunches at the
                # new world size and resumes from the checkpoint.
                log.warning("parked for resize (membership generation "
                            "%d): re-registering %s", self.mgen,
                            self.task_id)
                self._beacon_steps = None
                park_span = self.tracer.start_span(
                    "executor.park", parent=self._run_span,
                    task=self.task_id, attrs={"mgen": self.mgen})
                try:
                    cluster_spec = self.register_and_get_cluster_spec()
                except FencedError as e:
                    park_span.end(fenced=True)
                    log.error("park re-registration fenced for %s: %s",
                              self.task_id, e)
                    hb.stop()
                    self._run_span.end(fenced=True)
                    self._flush_trace()
                    return constants.EXIT_KILLED
                park_span.end(barrier_open=cluster_spec is not None)
                if cluster_spec is None:
                    log.error("post-resize barrier timed out for %s",
                              self.task_id)
                    hb.stop()
                    self._run_span.end(barrier_timeout=True)
                    self._flush_trace()
                    return constants.EXIT_FAILURE
                self._flush_trace()
        finally:
            if preempt_watcher is not None:
                preempt_watcher.stop()
            monitor.stop()
            if self.rendezvous_port.reuse:
                self.rendezvous_port.release()
            self._teardown_tensorboard(tb_proc)
        # A short task can finish before the heartbeater's next beacon
        # poll: read the final telemetry snapshot once more so the
        # first-step span lands even for one-step jobs (the bench probe).
        try:
            self._progress_beacon()
        except Exception:  # noqa: BLE001 — diagnostics only
            pass
        self._maybe_upload_profile()

        if self._released:
            # Released by a shrink: exit quietly with the preemption
            # shape. The coordinator absorbs the backend completion (the
            # task left the matrix at the re-mesh) — reporting a result
            # for a topology that no longer exists would only be fenced.
            hb.stop()
            log.warning("released from the gang by an elastic resize; "
                        "exiting")
            self._run_span.end(released=True)
            self._flush_trace()
            return constants.EXIT_PREEMPTED

        if self._orphaned_reason is not None:
            # The user process was stopped BY the orphan/fencing teardown:
            # there is no coordinator that wants this result (dead, or a
            # successor that fenced us out of a newer epoch). Reporting
            # the exit would be wrong on top of useless — a stale result
            # landing in a recovered session is exactly what the epoch
            # fence exists to stop.
            hb.stop()
            log.error("exiting as orphaned executor: %s",
                      self._orphaned_reason)
            self._run_span.end(orphaned=self._orphaned_reason)
            return constants.EXIT_KILLED
        hb.stop()
        # Close + ship the whole executor tree BEFORE reporting the
        # result: once the coordinator processes the exit it may tear the
        # epoch down, and these frames should already be in the log.
        self._run_span.end(exit_code=exit_code)
        self._flush_trace()
        self._report_result_with_recovery(
            exit_code, diagnostics=self._postmortem_diagnostics(exit_code))
        self._maybe_skew_sleep()
        return exit_code

    def _postmortem_diagnostics(self, exit_code: int) -> Optional[dict]:
        """Failed user process: extract the postmortem the coordinator
        can't reliably get itself — the last Python traceback from the
        task's own log tail (always local to THIS host, unlike the
        coordinator's view of it) and the decoded exit signal. Rides the
        result report into the TASK_FINISHED event and the incident
        bundle."""
        if exit_code == 0:
            return None
        from tony_tpu.diagnosis.exitcodes import describe_exit
        from tony_tpu.utils import logs as logutil

        diag: Dict[str, str] = {"exit_detail": describe_exit(exit_code)}
        for name in ("stderr.log", "stdout.log"):
            text = logutil.tail_text(os.path.join(os.getcwd(), name),
                                     64 * 1024)
            if not text:
                continue
            tb = logutil.extract_traceback(text)
            if tb:
                diag["traceback"] = tb
                break
        return diag

    def _report_result_with_recovery(
            self, exit_code: int,
            diagnostics: Optional[dict] = None) -> None:
        """Deliver the exit code, surviving a coordinator outage. A task
        that FINISHES while the coordinator is down would otherwise
        discard its result after one failed call — and the recovered
        coordinator, finding nobody to re-adopt, would burn a retry epoch
        re-running work that already completed (caught live in the
        recovery drill). Same contract as the heartbeat loop: re-resolve
        + retry inside the orphan deadline; a FencedError (stale epoch
        after a reset, or a superseding generation) is terminal — that
        result belongs to a world that no longer exists."""
        deadline = time.monotonic() + float(
            self.conf.get_int(K.TASK_ORPHAN_DEADLINE_S, 120))
        while True:
            try:
                self.client.call("register_execution_result",
                                 task_id=self.task_id, exit_code=exit_code,
                                 session_id=self.session_id,
                                 diagnostics=diagnostics)
                return
            except FencedError as e:
                log.warning("result for %s fenced by a live coordinator: "
                            "%s", self.task_id, e)
                return
            except Exception as e:  # noqa: BLE001
                if time.monotonic() >= deadline:
                    log.warning("failed to report execution result within "
                                "the orphan deadline: %s", e)
                    return
                log.info("result report failed (%s); re-resolving the "
                         "coordinator and retrying", e)
                time.sleep(1.0)
                self._resolve_coordinator()
                old, self.client = self.client, self._make_client(
                    self.coordinator_host, self.coordinator_port)
                old.close()

    def _maybe_upload_profile(self) -> None:
        """Remote-store jobs: ship the chief's captured traces home (the
        coordinator pulls them into the job dir at stop — see
        Coordinator._profile_store_url). Best-effort: a failed upload must
        not turn a finished task into a failure."""
        url = os.environ.get(constants.PROFILE_UPLOAD, "")
        local = os.environ.get(constants.PROFILE_DIR, "")
        if not url or not local:
            return
        local = os.path.join(os.getcwd(), local) \
            if not os.path.isabs(local) else local
        if not os.path.isdir(local):
            return
        try:
            from tony_tpu.storage import get_store

            get_store(url).put_tree(local, url)
            log.info("uploaded profiler traces to %s", url)
        except Exception as e:  # noqa: BLE001
            log.warning("profile upload failed: %s", e)

    def _maybe_launch_tensorboard(self, env: Dict[str, str]):
        """Chief-only: spawn the configured TensorBoard command on the
        reserved TB_PORT (the URL was registered at setup_ports; serving is
        new — the reference left launching to user scripts)."""
        cmd = str(self.conf.get(K.APPLICATION_TENSORBOARD_COMMAND, "") or "")
        if not cmd or not self.is_chief or self.tb_port is None:
            return None
        import subprocess

        full_env = dict(os.environ)
        full_env.update(env)
        log.info("chief launching tensorboard: %s", cmd)
        self._tb_log = open("tensorboard.log", "ab")
        try:
            return subprocess.Popen(cmd, shell=True, env=full_env,
                                    stdout=self._tb_log,
                                    stderr=subprocess.STDOUT)
        except Exception:
            self._tb_log.close()
            self._tb_log = None
            raise

    def _teardown_tensorboard(self, tb_proc) -> None:
        """Terminate→wait→kill escalation; must never raise — it runs in
        run()'s finally, after the user exit code is already in hand."""
        if tb_proc is not None:
            if tb_proc.poll() is None:
                tb_proc.terminate()
                try:
                    tb_proc.wait(timeout=5)
                except Exception:  # noqa: BLE001 — escalate to SIGKILL
                    tb_proc.kill()
                    try:
                        tb_proc.wait(timeout=5)
                    except Exception:  # noqa: BLE001 — unreapable; move on
                        log.warning("tensorboard process unreapable")
            log_f = getattr(self, "_tb_log", None)
            if log_f is not None:
                log_f.close()
                self._tb_log = None

    def _maybe_skew_sleep(self) -> None:
        """TEST_EXECUTOR_SKEW='job#idx#seconds' straggler simulation
        (reference :372-392)."""
        spec = os.environ.get(constants.TEST_EXECUTOR_SKEW, "")
        if not spec:
            return
        try:
            job, idx, seconds = spec.split("#")
            if job == self.job_name and int(idx) == self.index:
                log.warning("TEST hook: skew sleep %ss", seconds)
                time.sleep(float(seconds))
        except ValueError:
            log.warning("bad %s spec: %r", constants.TEST_EXECUTOR_SKEW, spec)


def main() -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s")
    # BEFORE anything talks to the network: the injected faults may target
    # the very RPC/storage calls that bootstrap this executor (fetching
    # the frozen config, registration) — env, not conf, carries the spec.
    from tony_tpu import faults

    faults.install_from_env()
    signal.signal(signal.SIGTERM, _forward_signal)
    signal.signal(signal.SIGINT, _forward_signal)
    executor = TaskExecutor()
    code = executor.run()
    return code


if __name__ == "__main__":
    sys.exit(main())
