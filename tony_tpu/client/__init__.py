from tony_tpu.client.client import (  # noqa: F401
    TaskUpdateListener, TonyTpuClient,
)
