"""Client library: submit a job, monitor it, mirror task state to listeners.

Reference model: ``TonyClient.java`` (1107 LoC) — merge config layers
(``initTonyConf`` :483), validate quotas (:598-667), stage the job bundle
(``processFinalTonyConf`` :189-228), build default task commands
(``buildTaskCommand`` :454-475), launch the per-job controller, poll the app
report and mirror task status to listeners (``monitorApplication`` :838,
``updateTaskInfos`` :894), signal shutdown (``finishApplication`` :886), and
force-kill on demand (:959). Callback surface mirrors
``client/CallbackHandler.java`` + ``client/TaskUpdateListener.java``.

TPU-first deltas: the "cluster" is a slice/host inventory rather than YARN —
the coordinator is spawned directly (locally today; a TPU-VM provisioner
backend slots in behind the same interface), and staging copies to a local
bundle dir instead of HDFS.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import subprocess
import sys
import time
import uuid
from typing import List, Optional

from tony_tpu import constants, tracing
from tony_tpu.conf.config import ConfigError, TonyTpuConfig
from tony_tpu.conf import keys as K
from tony_tpu.rpc.wire import RpcClient
from tony_tpu.utils import proc as procutil

log = logging.getLogger(__name__)


class TaskUpdateListener:
    """Programmatic-embedding hooks (reference ``TaskUpdateListener.java:14``
    + ``CallbackHandler.java:16``)."""

    def on_application_id_received(self, app_id: str) -> None:  # noqa: B027
        pass

    def on_task_infos_updated(self, task_infos: List[dict]) -> None:  # noqa: B027
        pass

    def on_application_report(self, report: dict) -> None:  # noqa: B027
        """Every poll, the raw coordinator report — mid-run state (tb_url,
        attempt, ...) that the task-info callback doesn't carry. Used by
        the notebook submitter to discover the server endpoint."""

    def on_application_finished(self, status: str, report: dict) -> None:  # noqa: B027
        pass


class TonyTpuClient:
    def __init__(self, conf: TonyTpuConfig,
                 workdir: Optional[str] = None):
        self.conf = conf
        self.workdir = workdir or os.environ.get(
            "TONY_TPU_WORKDIR",
            os.path.join(os.path.expanduser("~"), ".tony-tpu"))
        self.app_id: str = ""
        self.job_dir: str = ""
        self.listeners: List[TaskUpdateListener] = []
        self._coord_proc: Optional[subprocess.Popen] = None
        self._rpc: Optional[RpcClient] = None
        self._last_task_infos: List[dict] = []
        # Distributed tracing: the client is where the job's ONE trace
        # starts — the submit span is the root every coordinator/executor
        # span hangs under, and the anchor bench.py measures
        # submit→first-step from. Buffered locally, shipped over
        # trace.push once the coordinator answers its first report.
        # A FLEET-granted job adopts the fleet's trace id instead of
        # minting one (the daemon stamps tony.internal.fleet-trace-id
        # on the grant's conf), so `tony-tpu trace --fleet` renders the
        # whole pool — queue waits, grants, every job's lifecycle — on
        # one timeline.
        fleet_trace = str(conf.get(K.INTERNAL_FLEET_TRACE_ID, "")
                          or "")
        self._tracer = tracing.Tracer(
            trace_id=fleet_trace or None,
            service="client",
            enabled=conf.get_bool(K.TRACE_ENABLED, True))
        self._submit_span = tracing.NULL_SPAN
        self._trace_pushed = False

    # -- construction ----------------------------------------------------
    @classmethod
    def from_args(cls, config_file: Optional[str] = None,
                  overrides: tuple = (),
                  workdir: Optional[str] = None) -> "TonyTpuClient":
        """Reference ``TonyClient.init(args)`` :346 — parse layers, validate."""
        conf = TonyTpuConfig.from_layers(config_file=config_file,
                                         overrides=overrides)
        return cls(conf, workdir=workdir)

    def add_listener(self, listener: TaskUpdateListener) -> None:
        self.listeners.append(listener)

    # -- submit-time processing ------------------------------------------
    def _build_default_commands(self) -> None:
        """Jobtypes without a command get '<python> <executable> <params>'
        (reference ``buildTaskCommand`` :454-475)."""
        executable = str(self.conf.get(K.APPLICATION_EXECUTABLE, "") or "")
        params = str(self.conf.get(K.APPLICATION_TASK_PARAMS, "") or "")
        python = str(self.conf.get(K.PYTHON_BINARY_PATH, "") or "") \
            or sys.executable
        if str(self.conf.get(K.PYTHON_VENV, "") or "") and \
                not os.path.isabs(python):
            # The venv archive is unpacked to ./venv in every task workdir;
            # a relative interpreter resolves inside it (reference
            # ``TonyClient.buildTaskCommand`` venv interpreter :454-475).
            python = os.path.join("venv", python)
        jobs = self.conf.job_types()
        if not jobs and executable and \
                not str(self.conf.get(K.COORDINATOR_COMMAND, "") or ""):
            # Zero jobtypes → single-node mode: the coordinator runs the
            # command itself (reference ApplicationMaster.java:714).
            cmd = f"{python} {executable}"
            if params:
                cmd += f" {params}"
            self.conf.set(K.COORDINATOR_COMMAND, cmd)
            return
        for job in jobs.values():
            if job.command:
                continue
            if not executable:
                raise ConfigError(
                    f"jobtype {job.name!r} has no command and no "
                    f"{K.APPLICATION_EXECUTABLE} is set")
            cmd = f"{python} {executable}"
            if params:
                cmd += f" {params}"
            self.conf.set(K.COMMAND_FORMAT.format(job=job.name), cmd)

    def _storage_token(self) -> str:
        """Credential for the remote store: explicit conf key, else the
        submit environment (stamped into the frozen config either way —
        the delegation-token-shipped-with-the-job contract,
        ``security/TokenCache.java:44-51``)."""
        from tony_tpu.storage.store import STORAGE_TOKEN_ENV

        return str(self.conf.get(K.STORAGE_TOKEN, "") or "") \
            or os.environ.get(STORAGE_TOKEN_ENV, "")

    def _export_storage_token(self) -> str:
        """Resolve the storage credential and move it into the submit
        environment BEFORE the coordinator is spawned (the coordinator
        inherits this env and re-exports it to executors — the
        separate-token-file discipline of the reference,
        TokenCache.java:44-51). Scrubbed from the config UNCONDITIONALLY:
        the frozen config is world-readable (portal config view, events,
        the store itself), and a token set for e.g. gs:// checkpoint
        access must not freeze just because staging itself is local."""
        from tony_tpu.storage.store import STORAGE_TOKEN_ENV

        token = self._storage_token()
        if token:
            os.environ[STORAGE_TOKEN_ENV] = token
            self.conf.unset(K.STORAGE_TOKEN)
        return token

    def _stage_bundle(self, token: str = "") -> None:
        """Stage src-dir, container resources, and the python venv where
        executors can localize them (the HDFS-upload analogue,
        ``processFinalTonyConf`` :189-228). With ``tony.storage.
        remote-store`` set, everything is PUT to the object store under the
        job prefix and the internal keys carry store URLs — no shared
        filesystem between client and task hosts is assumed. Otherwise the
        job dir itself is the staging area (single-host path).

        The three groups (bundle tree, container resources, venv archive)
        are independent byte-copies, so they run CONCURRENTLY: validation
        happens up front in this thread (fail fast, before any copy
        starts), the copies fan out to a small thread pool, and the
        internal conf keys are set back here in submission order — the
        frozen config never depends on pool scheduling."""
        remote = str(self.conf.get(K.REMOTE_STORE, "") or "")
        store = prefix = None
        if remote:
            from tony_tpu.storage import get_store
            from tony_tpu.storage.store import join as ujoin

            store = get_store(remote, credential=token or None)
            prefix = ujoin(remote, self.app_id)
        src = str(self.conf.get(K.SRC_DIR, "") or "")
        resources = self.conf.get_list(K.CONTAINER_RESOURCES)
        venv = str(self.conf.get(K.PYTHON_VENV, "") or "")
        # Fail-fast validation BEFORE any bytes move.
        if src and not os.path.isdir(src):
            raise ConfigError(f"{K.SRC_DIR}={src!r} is not a directory")
        if venv and not os.path.isfile(venv):
            raise ConfigError(
                f"{K.PYTHON_VENV}={venv!r} is not an archive file")

        def stage_src() -> str:
            if store:
                from tony_tpu.storage.store import join as ujoin

                url = ujoin(prefix, "bundle")
                store.put_tree(src, url)
                return url
            bundle = os.path.join(self.job_dir, "bundle")
            shutil.copytree(src, bundle, dirs_exist_ok=True)
            return bundle

        def stage_res() -> str:
            from tony_tpu.utils.localize import stage_resources

            if store:
                from tony_tpu.storage.store import join as ujoin

                staged = stage_resources(resources, "", store=store,
                                         store_prefix=ujoin(prefix,
                                                            "resources"))
            else:
                staged = stage_resources(
                    resources, os.path.join(self.job_dir, "resources"))
            return ",".join(staged)

        def stage_venv() -> str:
            if store:
                from tony_tpu.storage.store import join as ujoin

                url = ujoin(prefix, os.path.basename(venv))
                store.put_file(venv, url)
                return url
            staged_venv = os.path.join(self.job_dir,
                                       os.path.basename(venv))
            shutil.copy2(venv, staged_venv)
            return staged_venv

        jobs = []
        if src:
            jobs.append((K.INTERNAL_BUNDLE_DIR, stage_src))
        if resources:
            jobs.append((K.INTERNAL_RESOURCES, stage_res))
        if venv:
            jobs.append((K.INTERNAL_VENV, stage_venv))
        if not jobs:
            return
        if len(jobs) == 1:
            # Nothing to overlap; skip the pool machinery.
            key, fn = jobs[0]
            self.conf.set(key, fn())
            return
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=len(jobs),
                                thread_name_prefix="tony-stage") as pool:
            futures = [(key, pool.submit(fn)) for key, fn in jobs]
            # .result() re-raises the first failure; remaining copies
            # finish in the pool's __exit__ — a partial staging area is
            # harmless, the job dir is per-app and about to be abandoned.
            for key, fut in futures:
                self.conf.set(key, fut.result())

    # -- lifecycle -------------------------------------------------------
    def start(self) -> int:
        """Submit + monitor to completion; returns a process exit code
        (reference ``run`` :155)."""
        self.conf.validate()
        self._build_default_commands()
        # Underscore-separated like YARN's application_<ts>_<n>: the history
        # filename grammar (history.py) uses '-' as its field separator.
        self.app_id = "app_%s_%s" % (time.strftime("%Y%m%d_%H%M%S"),
                                     uuid.uuid4().hex[:6])
        self.job_dir = os.path.join(self.workdir, "jobs", self.app_id)
        os.makedirs(self.job_dir, exist_ok=True)
        for lst in self.listeners:
            lst.on_application_id_received(self.app_id)
        # The fleet.job span id rides as an ATTR, not the span parent:
        # the job's own span tree stays self-contained (trace-parent
        # invariant), the --fleet export stitches by shared trace id.
        submit_attrs = {"app": self.app_id}
        fleet_parent = str(self.conf.get(
            K.INTERNAL_FLEET_TRACE_PARENT, "") or "")
        if fleet_parent:
            submit_attrs["fleet_parent"] = fleet_parent
        self._submit_span = self._tracer.start_span(
            "client.submit", attrs=submit_attrs)
        frozen = os.path.join(self.job_dir, constants.FINAL_CONFIG_FILE)
        addr_file = os.path.join(self.job_dir, "coordinator.addr")
        try:
            # Overlap the serial prefix: the coordinator process is
            # spawned FIRST — against a frozen-config path that does not
            # exist yet (its __main__ polls for it, --conf-wait-s) — so
            # its interpreter boot, imports, and backend construction run
            # CONCURRENTLY with the client-side staging copies below.
            # The credential export must precede the spawn (the
            # coordinator inherits this env).
            token = self._export_storage_token()
            self._spawn_coordinator(frozen, addr_file)
            stage_span = self._tracer.start_span(
                "client.stage", parent=self._submit_span,
                attrs={"parallel": True})
            try:
                self._stage_bundle(token)
            finally:
                stage_span.end()
            self.conf.set(K.INTERNAL_APP_ID, self.app_id)
            from tony_tpu.utils.version import version_info

            vi = version_info()
            self.conf.set(K.INTERNAL_VERSION, vi["version"])
            self.conf.set(K.INTERNAL_REVISION, vi["revision"])
            self.conf.set(K.INTERNAL_BRANCH, vi["branch"])
            remote = str(self.conf.get(K.REMOTE_STORE, "") or "")
            conf_url = ""
            if remote:
                # Executors on remote hosts fetch the frozen config itself
                # from the store; the URL must be IN the config for the
                # coordinator to hand out, so set it before freezing.
                from tony_tpu.storage.store import join as ujoin

                conf_url = ujoin(remote, self.app_id,
                                 constants.FINAL_CONFIG_FILE)
                self.conf.set(K.INTERNAL_CONF_URL, conf_url)
            # Atomic (tmp+rename, utils/durable.py): the waiting
            # coordinator must never read a partial config.
            self.conf.freeze(frozen)
            if conf_url:
                from tony_tpu.storage import get_store

                get_store(remote, credential=token or None
                          ).put_file(frozen, conf_url)
            return self._monitor(addr_file)
        except RuntimeError as e:
            # Coordinator died before/while serving (reference returns -1
            # from monitorApplication on a failed app report, :838-892).
            log.error("submission failed: %s", e)
            return constants.EXIT_FAILURE
        finally:
            # Also reached on a staging ConfigError: the already-spawned
            # coordinator (still waiting for the config) must not leak.
            self._cleanup()

    def _spawn_coordinator(self, frozen: str, addr_file: str) -> None:
        history_root = str(self.conf.get(K.HISTORY_LOCATION, "") or "") \
            or os.path.join(self.workdir, "history")
        cmd = [sys.executable, "-m", "tony_tpu.coordinator",
               "--conf", frozen, "--conf-wait-s", "600",
               "--app-id", self.app_id,
               "--history-root", history_root,
               "--workdir", os.path.join(self.job_dir, "tasks"),
               "--addr-file", addr_file,
               "--user", os.environ.get("USER", "unknown")]
        coord_log = open(os.path.join(self.job_dir, "coordinator.log"), "wb")
        env = dict(os.environ)
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = (repo_root + os.pathsep +
                             env.get("PYTHONPATH", "")).rstrip(os.pathsep)
        if self._tracer.enabled:
            # The coordinator's run span parents under this submit span.
            env[constants.TRACE_ID_ENV] = self._tracer.trace_id
            env[constants.TRACE_PARENT_ENV] = self._submit_span.span_id
        self._coord_proc = subprocess.Popen(
            cmd, stdout=coord_log, stderr=subprocess.STDOUT, env=env)
        coord_log.close()

    def _connect(self, addr_file: str) -> RpcClient:
        """Poll for the coordinator endpoint (the RM-report analogue)."""
        def read_addr() -> Optional[dict]:
            if self._coord_proc and self._coord_proc.poll() is not None:
                raise RuntimeError(
                    f"coordinator exited early with "
                    f"{self._coord_proc.returncode}; see "
                    f"{os.path.join(self.job_dir, 'coordinator.log')}")
            if os.path.exists(addr_file):
                with open(addr_file, encoding="utf-8") as f:
                    return json.load(f)
            return None

        # Generous window: since the overlapped-submit change the
        # coordinator only binds its port AFTER the client finishes
        # staging and freezes the config, so big remote stagings push the
        # address file out by minutes. A dead coordinator is still
        # detected within one 0.1 s poll (read_addr raises), so the long
        # timeout only bounds the pathological silent-hang case.
        addr = procutil.poll_till_non_null(read_addr, interval_s=0.1,
                                           timeout_s=600)
        if addr is None:
            raise RuntimeError("coordinator address never appeared")
        tls = None
        if addr.get("tls_cert"):
            from tony_tpu.rpc.wire import client_tls_context
            tls = client_tls_context(addr["tls_cert"])
        # Short INNER retry budget: the monitor loop around this client
        # already retries forever (with a coordinator-liveness check per
        # failure) — stacking the transport's default 10×2 s on top only
        # delayed dead-coordinator detection by ~20 s.
        return RpcClient(addr["host"], addr["port"],
                         token=addr.get("token") or None, tls=tls,
                         max_retries=3, retry_sleep_s=0.5, peer="coordinator")

    def _monitor(self, addr_file: str) -> int:
        """Reference ``monitorApplication`` :838-892 (1 s poll; task-info
        diffs to listeners; terminal status → finishApplication)."""
        self._rpc = self._connect(addr_file)
        interval = self.conf.get_int(K.CLIENT_POLL_INTERVAL_MS, 1000) / 1000.0
        while True:
            try:
                report = self._rpc.call("get_application_report")
            except Exception as e:  # noqa: BLE001
                if self._coord_proc and self._coord_proc.poll() is not None:
                    log.error("coordinator died: %s", e)
                    return constants.EXIT_FAILURE
                time.sleep(interval)
                continue
            if not self._trace_pushed:
                # First answered report: the app is live — close the
                # submit span and ship the client's spans into the job's
                # span log (best-effort; the trace survives without them).
                self._trace_pushed = True
                self._submit_span.end(status=report.get("status", ""))
                records = self._tracer.drain()
                if records:
                    try:
                        self._rpc.call("trace.push", records=records)
                    except Exception:  # noqa: BLE001
                        pass
            tasks = report.get("tasks", [])
            if tasks != self._last_task_infos:
                self._last_task_infos = tasks
                for lst in self.listeners:
                    lst.on_task_infos_updated(tasks)
            for lst in self.listeners:
                try:
                    lst.on_application_report(report)
                except Exception as e:  # noqa: BLE001
                    # A listener failure (e.g. the notebook proxy's local
                    # port already bound) must not tear down a running job.
                    log.warning("listener %s.on_application_report "
                                "failed: %s", type(lst).__name__, e)
            status = report.get("status", "")
            if status in ("SUCCEEDED", "FAILED", "KILLED"):
                for lst in self.listeners:
                    lst.on_application_finished(status, report)
                try:
                    self._rpc.call("finish_application")
                except Exception:  # noqa: BLE001
                    pass
                # Let the coordinator finalize events/history before we
                # return (it tears down after the finish signal,
                # reference stop() :670-688).
                if self._coord_proc is not None:
                    try:
                        self._coord_proc.wait(timeout=30)
                    except subprocess.TimeoutExpired:
                        log.warning("coordinator slow to exit; killing")
                if status != "SUCCEEDED" and report.get("failure_reason"):
                    domain = report.get("failure_domain", "")
                    log.error("application %s%s: %s", status,
                              f" [{domain}]" if domain else "",
                              report["failure_reason"])
                return 0 if status == "SUCCEEDED" else constants.EXIT_FAILURE
            time.sleep(interval)

    def force_kill(self) -> None:
        """Reference ``forceKillApplication`` :959 + the CLI kill-on-exit
        shutdown hook (``ClusterSubmitter.java:69``)."""
        try:
            if self._rpc is not None:
                self._rpc.call("kill_application")
        except Exception:  # noqa: BLE001
            pass
        if self._coord_proc is not None and self._coord_proc.poll() is None:
            # The coordinator's teardown legitimately takes up to TWO
            # grace windows (kill ladder in _monitor, then _stop's
            # client-finish wait when nothing signals finish — the Ctrl-C
            # path) — wait them out before escalating, or the escalation
            # itself orphans user processes mid-preemption-save and
            # leaves history unfinalized.
            from tony_tpu.conf import keys as K

            grace = self.conf.get_int(K.COORDINATOR_STOP_GRACE_S, 15)
            try:
                self._coord_proc.wait(timeout=2 * grace + 15)
            except subprocess.TimeoutExpired:
                self._coord_proc.terminate()

    def _cleanup(self) -> None:
        if self._rpc is not None:
            self._rpc.close()
        if self._coord_proc is not None and self._coord_proc.poll() is None:
            self._coord_proc.terminate()
            try:
                self._coord_proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self._coord_proc.kill()

    # -- introspection ---------------------------------------------------
    @property
    def task_infos(self) -> List[dict]:
        return list(self._last_task_infos)
