"""Data-parallel MNIST: the canonical first job (the analogue of the
reference's ``tony-examples/mnist-tensorflow`` / ``mnist-pytorch``, but one
uniform JAX bootstrap instead of per-framework env dialects).

Synthetic data (no dataset download — swap in real MNIST loading where you
have network/disk). Multi-process: the tony-tpu JAXRuntime provides
JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID; single
process runs standalone on whatever chips are visible.
"""
import os
import sys

import jax

# Some images pre-import jax via sitecustomize pinned to the real
# accelerator; honour an explicit CPU request (virtual-mesh runs).
if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    jax.config.update("jax_platforms", "cpu")

if int(os.environ.get("JAX_NUM_PROCESSES", "1")) > 1:
    jax.distributed.initialize(
        coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
        num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
        process_id=int(os.environ["JAX_PROCESS_ID"]))

import jax.numpy as jnp
import optax

from tony_tpu.models import MnistMLP
from tony_tpu.models.mlp import classification_loss
from tony_tpu.parallel import (MeshSpec, build_mesh, init_sharded_state,
                               jit_train_step)

STEPS = int(os.environ.get("MNIST_STEPS", "20"))

mesh = build_mesh(MeshSpec(dp=-1))          # pure data parallelism
model = MnistMLP(hidden=128)
x = jax.random.normal(jax.random.key(0), (64, 28, 28, 1))
y = jax.random.randint(jax.random.key(1), (64,), 0, 10)
batch = {"x": x, "y": y}


def loss_fn(params, b, rng):
    return classification_loss(model.apply({"params": params}, b["x"]),
                               b["y"]), {}


state, state_sh = init_sharded_state(model, x, optax.adam(1e-2), mesh)
step = jit_train_step(loss_fn, mesh, state_sh, batch)
first = last = None
for i in range(STEPS):
    state, m = step(state, batch, jax.random.key(i))
    last = float(m["loss"])
    first = first if first is not None else last
print(f"process {jax.process_index()}: loss {first:.4f} -> {last:.4f}")
assert last < first, "loss did not decrease"
if jax.process_count() > 1:
    jax.distributed.shutdown()
sys.exit(0)
