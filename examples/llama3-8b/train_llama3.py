"""Llama-3-8B pretraining step: fsdp x tp sharding, flash attention,
bf16 activations, f32 params, checkpoint/resume via the job checkpoint
dir. The flagship target (BASELINE.json): geometry from the public
Llama-3-8B config (32L / 4096d / 32h / 8kv / 14336 mlp / 128k vocab)."""
import os
import sys

import jax

# Some images pre-import jax via sitecustomize pinned to the real
# accelerator; honour an explicit CPU request (virtual-mesh runs).
if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    jax.config.update("jax_platforms", "cpu")

if int(os.environ.get("JAX_NUM_PROCESSES", "1")) > 1:
    jax.distributed.initialize(
        coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
        num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
        process_id=int(os.environ["JAX_PROCESS_ID"]))

import functools

import flax.linen as nn
import jax.numpy as jnp
import optax

from tony_tpu.checkpoint import CheckpointManager
from tony_tpu.models import Transformer, TransformerConfig
from tony_tpu.models.transformer import (causal_lm_loss,
                                         chunked_causal_lm_loss)
from tony_tpu.parallel import MeshSpec, build_mesh, init_sharded_state
from tony_tpu.parallel.sharding import DEFAULT_RULES

BATCH = int(os.environ.get("LLAMA_BATCH", "8"))
SEQ = int(os.environ.get("LLAMA_SEQ", "8192"))
STEPS = int(os.environ.get("LLAMA_STEPS", "100"))
TP = int(os.environ.get("LLAMA_TP", "4"))
# The tony.train.* hot-loop knobs, env-shaped for this script:
# accumulation + bucketed DCN grad sync (parallel/grad_sync.py) and the
# quantized projection path (ops/quant.py). Defaults = monolithic step,
# bf16 — the pre-grad-sync behaviour, bitwise.
ACCUM = int(os.environ.get("LLAMA_ACCUM_STEPS", "1"))
BUCKET_MB = int(os.environ.get("LLAMA_BUCKET_MB", "32"))
MATMUL_DTYPE = os.environ.get("LLAMA_MATMUL_DTYPE", "")

if os.environ.get("LLAMA_TINY"):
    # CI shape: same code path (mesh, remat policy, checkpointing), toy
    # geometry — lets the flagship script run on the virtual CPU mesh.
    cfg = TransformerConfig.tiny(
        n_layers=2, remat=True,
        remat_policy="dots_with_no_batch_dims_saveable",
        matmul_dtype=MATMUL_DTYPE or None)
else:
    cfg = TransformerConfig.llama3_8b(
        remat=True, remat_policy="dots_with_no_batch_dims_saveable",
        # RoPE guard bound: follow the requested context (llama3's native
        # window is 8192; longer runs are context extension on synthetic
        # data here).
        max_seq_len=max(SEQ, 8192),
        matmul_dtype=MATMUL_DTYPE or None)
mesh = build_mesh(MeshSpec(dp=1, fsdp=-1, tp=TP))
model = Transformer(cfg)
tokens = jax.random.randint(jax.random.key(0), (BATCH, SEQ), 0,
                            cfg.vocab_size)  # synthetic; wire your loader

state, state_sh = init_sharded_state(
    model, tokens, optax.adamw(3e-4, weight_decay=0.1), mesh)


# Past ~8k context the [B, S, 128k-vocab] logits (not attention) are the
# memory wall: the chunked loss never materializes them. Short sequences
# keep the one-matmul full path. LLAMA_CHUNKED_LOSS=1 forces the chunked
# branch (CI exercises it at toy geometry).
LOSS_CHUNK = int(os.environ.get("LLAMA_LOSS_CHUNK", "2048"))
CHUNKED = SEQ >= 8192 or os.environ.get("LLAMA_CHUNKED_LOSS") == "1"


def _loss_on(params, toks):
    with nn.logical_axis_rules(list(DEFAULT_RULES)):
        if CHUNKED:
            h = model.apply({"params": params}, toks, return_hidden=True)
            return chunked_causal_lm_loss(
                h, params["lm_head"]["kernel"], toks,
                chunk_size=LOSS_CHUNK, head_dtype=cfg.lm_head_dtype)
        return causal_lm_loss(model.apply({"params": params}, toks), toks)


def loss(params):
    return _loss_on(params, tokens)


if ACCUM > 1:
    # Grad-sync path: ACCUM microbatches per optimizer step, bucketed
    # cross-slice all-reduce as its own telemetry-phased dispatch — the
    # step `top`/perf.json can attribute a comms fraction to.
    from tony_tpu.parallel import jit_train_step_accum

    def _loss_fn(params, b, rng):
        return _loss_on(params, b["tokens"]), {}

    _gstep = jit_train_step_accum(
        _loss_fn, mesh, state_sh, {"tokens": tokens},
        accum_steps=ACCUM, bucket_mb=BUCKET_MB, donate=False)

    def step(state):
        state, metrics = _gstep(state, {"tokens": tokens},
                                jax.random.key(0))
        return state, metrics["loss"]
else:
    @functools.partial(jax.jit, donate_argnums=0)
    def step(state):
        l, grads = jax.value_and_grad(loss)(state.params)
        return state.apply_gradients(grads), l


ckpt_dir = os.environ.get("TONY_CHECKPOINT_DIR", "")
mgr = CheckpointManager(ckpt_dir, save_interval_steps=50) if ckpt_dir \
    else None
start = 0


def _ckpt_tree(s):
    # FULL state: params alone would resume with re-warming Adam moments
    # and a reset step counter — a loss spike after every restart.
    return {"step": s.step, "params": s.params, "opt_state": s.opt_state}


if mgr is not None and mgr.latest_step() is not None:
    try:
        state = state.replace(**mgr.restore(mgr.latest_step(),
                                            _ckpt_tree(state)))
    except Exception:  # noqa: BLE001 — pre-full-state checkpoint layout
        print("warning: checkpoint has no opt_state (older layout); "
              "resuming with params only — optimizer moments re-warm",
              file=sys.stderr)
        partial = {"step": state.step, "params": state.params}
        state = state.replace(**mgr.restore(mgr.latest_step(), partial))
    # Checkpoint i is saved AFTER loop iteration i (post-step state), so
    # the next iteration to run is i+1 — resuming at i would duplicate
    # one optimizer update per restart.
    start = int(mgr.latest_step()) + 1

# Per-step utilization (steps/s, duty cycle, MFU) flows to TASK_FINISHED
# metrics and the portal's /metrics view via the telemetry reporter — the
# TPU analogue of per-container GPU util (TaskMonitor.java:116-170).
from tony_tpu import telemetry

n_params = sum(x.size for x in jax.tree.leaves(state.params))
flops_per_step = 6 * n_params * BATCH * SEQ
for i in range(start, STEPS):
    with telemetry.step(flops=flops_per_step, tokens=BATCH * SEQ):
        state, l = step(state)
        jax.block_until_ready(l)
    if mgr is not None:
        mgr.save(i, _ckpt_tree(state))
if mgr is not None:
    mgr.wait()
print(f"process {jax.process_index()}: final loss {float(l):.4f}")
if jax.process_count() > 1:
    jax.distributed.shutdown()
sys.exit(0)
