"""Mixture-of-experts training with expert parallelism: experts live on
the `ep` mesh axis, tokens reach them via all_to_all dispatch
(tony_tpu/models/moe.py). New capability relative to the reference, which
never sharded a model across tasks (SURVEY.md section 2.3)."""
import os
import sys

import jax

# Some images pre-import jax via sitecustomize pinned to the real
# accelerator; honour an explicit CPU request (virtual-mesh runs).
if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    jax.config.update("jax_platforms", "cpu")

if int(os.environ.get("JAX_NUM_PROCESSES", "1")) > 1:
    jax.distributed.initialize(
        coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
        num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
        process_id=int(os.environ["JAX_PROCESS_ID"]))

import jax.numpy as jnp
import optax

from tony_tpu import compat
from tony_tpu.models.moe import MoEConfig, MoETransformer, moe_lm_loss
from tony_tpu.parallel import MeshSpec, build_mesh, init_sharded_state
from tony_tpu.parallel.sharding import DEFAULT_RULES

import flax.linen as nn
import functools

STEPS = int(os.environ.get("MOE_STEPS", "5"))
EP = int(os.environ.get("MOE_EP", "2"))

mesh = build_mesh(MeshSpec(dp=-1, ep=EP))
cfg = MoEConfig.tiny_moe()
model = MoETransformer(cfg)
tokens = jax.random.randint(jax.random.key(0), (8, 32), 0, cfg.vocab_size)

state, state_sh = init_sharded_state(model, tokens, optax.adam(1e-3), mesh)


def loss(params):
    with nn.logical_axis_rules(list(DEFAULT_RULES)):
        out = model.apply({"params": params}, tokens)
        return moe_lm_loss(out, tokens, aux_weight=cfg.aux_loss_weight)


@jax.jit
def step(state):
    l, grads = jax.value_and_grad(loss)(state.params)
    return state.apply_gradients(grads), l


# telemetry.step feeds utilization into TASK_FINISHED metrics / the
# portal /metrics view when run under tony-tpu.
from tony_tpu import telemetry

first = last = None
with compat.set_mesh(mesh):
    for i in range(STEPS):
        with telemetry.step():
            state, l = step(state)
            last = float(l)
        first = first if first is not None else last
print(f"process {jax.process_index()}: loss {first:.4f} -> {last:.4f}")
assert last < first, "loss did not decrease"
if jax.process_count() > 1:
    jax.distributed.shutdown()
sys.exit(0)
