#!/usr/bin/env bash
# Submit a job onto a provisioned slice with the ssh provisioner.
#
# Usage: HOSTS=ip1,ip2,... ./run-job.sh path/to/job-config.yaml
set -euo pipefail

CONF=${1:?job config file}
: "${HOSTS:?comma-separated TPU VM hosts (from create-tpu-slice.sh)}"

N_HOSTS=$(awk -F, '{print NF}' <<<"$HOSTS")
python -m tony_tpu.cli submit --conf-file "$CONF" \
    --conf tony.application.backend=tpu-slice \
    --conf tony.slice.provisioner=ssh \
    --conf "tony.slice.hosts=$HOSTS" \
    --conf "tony.slice.num-hosts=$N_HOSTS"
