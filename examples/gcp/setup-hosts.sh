#!/usr/bin/env bash
# Install tony-tpu + jax[tpu] on every host of a slice (runs the command
# on all workers via the TPU VM ssh fanout).
#
# Usage: ./setup-hosts.sh NAME ZONE [WHEEL_OR_GIT_URL]
set -euo pipefail

NAME=${1:?slice name}
ZONE=${2:?zone}
SRC=${3:-tony-tpu}

gcloud compute tpus tpu-vm ssh "$NAME" --zone="$ZONE" --worker=all \
    --command="pip install -U 'jax[tpu]' -f https://storage.googleapis.com/jax-releases/libtpu_releases.html && pip install '$SRC'"
