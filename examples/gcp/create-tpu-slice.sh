#!/usr/bin/env bash
# Provision a Cloud TPU pod slice for tony-tpu jobs (the analogue of the
# reference's tony-in-gcp Dataproc setup scripts — here the substrate is
# TPU VMs instead of a Hadoop cluster).
#
# Usage: ./create-tpu-slice.sh NAME ZONE ACCEL_TYPE [VERSION]
#   e.g. ./create-tpu-slice.sh tony-v5p us-east5-a v5p-32
set -euo pipefail

NAME=${1:?slice name}
ZONE=${2:?zone, e.g. us-east5-a}
TYPE=${3:?accelerator type, e.g. v5p-32}
VERSION=${4:-tpu-ubuntu2204-base}

gcloud compute tpus tpu-vm create "$NAME" \
    --zone="$ZONE" \
    --accelerator-type="$TYPE" \
    --version="$VERSION"

# The per-host inventory for tony.slice.hosts (ssh provisioner):
gcloud compute tpus tpu-vm describe "$NAME" --zone="$ZONE" \
    --format='value(networkEndpoints[].ipAddress)' | tr ';' ','
