"""PyTorch DDP MNIST-shaped training through the tony-tpu PyTorchRuntime.

The reference parity example (``tony-examples/mnist-pytorch``): the
coordinator's gang barrier produces the rendezvous env — INIT_METHOD /
MASTER_ADDR / MASTER_PORT / RANK / WORLD_SIZE (``PyTorchRuntime``,
reference ``TaskExecutor.java:169-179``) — and this script consumes it
with vanilla ``torch.distributed`` + DDP over gloo (CPU; on GPU pools the
same script works with nccl). Data is synthetic MNIST-shaped (28×28
digits): this environment has zero egress, and the point is the
orchestration contract, not the dataset.

Run it as a 2-worker gang:
    tony-tpu submit --conf-file mnist.json \
        --conf "tony.worker.command=python mnist_ddp.py"
"""
import os

import torch
import torch.distributed as dist
import torch.nn as nn
from torch.nn.parallel import DistributedDataParallel as DDP

STEPS = int(os.environ.get("MNIST_STEPS", "30"))
BATCH = int(os.environ.get("MNIST_BATCH", "64"))

rank = int(os.environ["RANK"])
world = int(os.environ["WORLD_SIZE"])
dist.init_process_group("gloo", init_method=os.environ["INIT_METHOD"],
                        rank=rank, world_size=world)

torch.manual_seed(0)   # identical init everywhere; DDP keeps it in sync
model = DDP(nn.Sequential(
    nn.Flatten(), nn.Linear(28 * 28, 128), nn.ReLU(), nn.Linear(128, 10)))
opt = torch.optim.SGD(model.parameters(), lr=0.1)
loss_fn = nn.CrossEntropyLoss()

# Per-rank shard of a deterministic synthetic set: each class is a noisy
# fixed template, so the model has real structure to learn.
g = torch.Generator().manual_seed(1234 + rank)
templates = torch.rand((10, 28, 28), generator=torch.Generator().manual_seed(7))
labels = torch.randint(0, 10, (STEPS * BATCH,), generator=g)
images = templates[labels] + 0.3 * torch.rand((len(labels), 28, 28),
                                              generator=g)

first = last = None
for step in range(STEPS):
    x = images[step * BATCH:(step + 1) * BATCH]
    y = labels[step * BATCH:(step + 1) * BATCH]
    opt.zero_grad()
    loss = loss_fn(model(x), y)
    loss.backward()        # DDP allreduces gradients across the gang here
    opt.step()
    first = loss.item() if first is None else first
    last = loss.item()

# Cross-rank agreement: DDP-synced params must be identical everywhere.
probe = next(model.parameters()).detach().clone()
gathered = [torch.zeros_like(probe) for _ in range(world)]
dist.all_gather(gathered, probe)
assert all(torch.equal(t, gathered[0]) for t in gathered), \
    "ranks diverged — gradient allreduce broken"

print(f"rank {rank}/{world}: loss {first:.4f} -> {last:.4f}")
assert last < first, "loss should decrease"
dist.destroy_process_group()
