"""Gang worker: discover the head from CLUSTER_SPEC and talk to it.

The generic runtime exports only ``CLUSTER_SPEC`` (a JSON
``{jobtype: ["host:port", ...]}`` map) plus the task identity — the same
contract ray-on-tony's ``discovery.py:30-36`` parses out of TF_CONFIG.
Each worker writes its own key to the head's store, then reads back every
worker's key to prove the gang shares one service.

Connections retry: between the gang barrier and the head process binding
its port there is a window where the head's *reserved* port accepts the
TCP handshake (the executor's reservation socket holds it) and then
resets on release-before-exec — any real client of a gang service
(Ray workers included) reconnects through that window.
"""
import json
import os
import socket
import time

spec = json.loads(os.environ["CLUSTER_SPEC"])
host, _, port = spec["head"][0].rpartition(":")
me = f'{os.environ["JOB_NAME"]}:{os.environ["TASK_INDEX"]}'
n_workers = len(spec["worker"])
DEADLINE = time.time() + 90


def rpc(line):
    """One connect-send-recv round trip, retried until the head is up."""
    while True:
        try:
            with socket.create_connection((host, int(port)),
                                          timeout=10) as s:
                s.sendall((line + "\n").encode())
                reply = s.makefile("rb").readline().decode().strip()
                if reply:
                    return reply
        except OSError:
            pass
        if time.time() > DEADLINE:
            raise SystemExit(f"head at {host}:{port} never answered {line!r}")
        time.sleep(0.2)


assert rpc(f"PUT {me} hello-from-{me}") == "OK"
# Barrier-by-polling: wait until every worker's key is present.
while True:
    got = [rpc(f"GET worker:{i}") for i in range(n_workers)]
    if all(g.startswith("VAL ") for g in got):
        break
    if time.time() > DEADLINE:
        raise SystemExit(f"peers never appeared: {got}")
    time.sleep(0.2)
print(f"{me} saw {got}", flush=True)
