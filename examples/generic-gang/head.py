"""Gang "head" service — the Ray-head stand-in.

Binds the rendezvous port the executor reserved for this task
(``TASK_PORT``) and serves a one-line key-value protocol
(``PUT k v`` / ``GET k``) until killed. The head jobtype is *untracked*
(like the reference's parameter servers, ``TonyConfigurationKeys.java:252``):
it runs for the life of the job and the coordinator kills it once every
tracked worker has finished — exactly the ray-on-tony lifecycle
(``tony-examples/ray-on-tony/README.md``).
"""
import os
import socketserver

store = {}


class Handler(socketserver.StreamRequestHandler):
    def handle(self):
        for raw in self.rfile:
            parts = raw.decode().strip().split(" ", 2)
            if parts[0] == "PUT" and len(parts) == 3:
                store[parts[1]] = parts[2]
                self.wfile.write(b"OK\n")
            elif parts[0] == "GET" and len(parts) == 2:
                v = store.get(parts[1])
                self.wfile.write(
                    (f"VAL {v}\n" if v is not None else "NONE\n").encode())
            else:
                self.wfile.write(b"ERR\n")
            self.wfile.flush()


class Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True


port = int(os.environ["TASK_PORT"])
print(f"head serving on :{port}", flush=True)
Server(("", port), Handler).serve_forever()
