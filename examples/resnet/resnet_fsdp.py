"""ResNet image classification with dp x fsdp sharding (reference
analogue: the examples tree's vision workload; here the model weights are
fully sharded over the fsdp axis, gradients reduced over dp)."""
import os
import sys

import jax

# Some images pre-import jax via sitecustomize pinned to the real
# accelerator; honour an explicit CPU request (virtual-mesh runs).
if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
    jax.config.update("jax_platforms", "cpu")

if int(os.environ.get("JAX_NUM_PROCESSES", "1")) > 1:
    jax.distributed.initialize(
        coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
        num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
        process_id=int(os.environ["JAX_PROCESS_ID"]))

import jax.numpy as jnp
import optax

from tony_tpu.models.mlp import classification_loss
from tony_tpu.models.resnet import ResNet, ResNetConfig
from tony_tpu.parallel import (MeshSpec, build_mesh, init_sharded_state,
                               jit_train_step)

STEPS = int(os.environ.get("RESNET_STEPS", "10"))
FSDP = int(os.environ.get("RESNET_FSDP", "2"))

mesh = build_mesh(MeshSpec(dp=-1, fsdp=FSDP))
cfg = ResNetConfig.tiny() if os.environ.get("RESNET_TINY", "1") == "1" \
    else ResNetConfig.resnet50()
model = ResNet(cfg)
x = jax.random.normal(jax.random.key(0), (16, 32, 32, 3))
y = jax.random.randint(jax.random.key(1), (16,), 0, cfg.num_classes)
batch = {"x": x, "y": y}


def loss_fn(params, b, rng):
    return classification_loss(model.apply({"params": params}, b["x"]),
                               b["y"]), {}


state, state_sh = init_sharded_state(model, x, optax.adam(1e-3), mesh)
step = jit_train_step(loss_fn, mesh, state_sh, batch)
# telemetry.step feeds utilization (steps/s, duty cycle) into the job's
# TASK_FINISHED metrics and the portal /metrics view when run under
# tony-tpu; standalone it is a no-op beyond a timestamp.
from tony_tpu import telemetry

first = last = None
for i in range(STEPS):
    with telemetry.step():
        state, m = step(state, batch, jax.random.key(i))
        last = float(m["loss"])
    first = first if first is not None else last
print(f"process {jax.process_index()}: loss {first:.4f} -> {last:.4f}")
assert last < first, "loss did not decrease"
if jax.process_count() > 1:
    jax.distributed.shutdown()
sys.exit(0)
